"""Attention kernels: naive, blockwise (FlashAttention-style online
softmax in pure jax), and a Pallas TPU flash-attention forward kernel.

Layouts: q (B, Sq, Hq, D); k/v (B, Skv, Hkv, D). GQA when Hkv < Hq.

Dispatch policy (``attention``):
  * TPU → Pallas flash kernels for BOTH directions: forward (MXU-tiled,
    VMEM online-softmax accumulation, causal blocks skipped, LSE saved)
    and backward (dq + dkv kernels rebuilding softmax from the LSE —
    ~4x the throughput of a blockwise-recompute VJP).
  * everywhere else (CPU tests, unaligned shapes) → blockwise jax
    implementation; XLA fuses it well and autodiff gives a
    memory-efficient backward when wrapped in jax.checkpoint.

The reference has no attention of its own (tensors are torch's problem —
SURVEY §2.3/§5.7); these kernels are net-new TPU substrate.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is importable even on CPU-only processes
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _repeat_kv(k, n_rep: int):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)
                            ).reshape(b, s, h * n_rep, d)


def naive_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None):
    """Reference O(S^2)-memory attention (correctness oracle for tests)."""
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    k = _repeat_kv(k, hq // hkv)
    v = _repeat_kv(v, hq // hkv)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        qi = jnp.arange(sq)[:, None] + (skv - sq)
        ki = jnp.arange(skv)[None, :]
        logits = jnp.where(ki <= qi, logits, NEG_INF)
    # Masked softmax with all-masked rows producing zeros (not uniform).
    m = logits.max(axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    p = jnp.where(logits > NEG_INF * 0.5, p, 0.0)
    denom = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhqk,bkhd->bqhd", p / denom, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Blockwise attention: lax.scan over kv chunks with online softmax.
# Differentiable; O(S * block) memory per step.
# ---------------------------------------------------------------------------


def blockwise_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        kv_block: int = 512):
    """FlashAttention recurrence in jax: scan kv blocks, track (m, l, acc)."""
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    n_rep = hq // hkv
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)

    kv_block = min(kv_block, skv)
    pad = (-skv) % kv_block
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_blocks = (skv + pad) // kv_block

    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(b, n_blocks, kv_block, hq, d)
    vf = v.astype(jnp.float32).reshape(b, n_blocks, kv_block, hq, d)
    # scan over blocks: move block axis to front
    kf = jnp.moveaxis(kf, 1, 0)
    vf = jnp.moveaxis(vf, 1, 0)

    q_pos = jnp.arange(sq)[:, None] + (skv - sq)

    def step(carry, blk):
        m, l, acc, j = carry
        kb, vb = blk
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kb)
        k_pos = j * kv_block + jnp.arange(kv_block)[None, :]
        mask = k_pos < skv  # padding mask, shape (1, kv_block)
        if causal:
            mask = mask & (k_pos <= q_pos)  # (sq, kv_block)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        # Keep fully-masked rows at zero weight: exp(NEG_INF - NEG_INF)
        # would otherwise be 1 and attend uniformly (incl. padding).
        p = jnp.where(logits > NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vb)
        return (m_new, l_new, acc_new, j + 1), None

    m0 = jnp.full((b, hq, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hq, sq), jnp.float32)
    acc0 = jnp.zeros((b, hq, sq, d), jnp.float32)
    (m, l, acc, _), _ = jax.lax.scan(step, (m0, l0, acc0, 0), (kf, vf))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention forward.
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      scale, causal, block_q, block_k, seq_q, seq_k):
    # grid = (batch*heads_q, q_blocks, kv_blocks); kv innermost/sequential.
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_off = seq_k - seq_q  # causal alignment for self-attn with cache
    run = True
    if causal:
        # Whole block above the diagonal → skip all compute.
        run = (j * block_k) <= (i * block_q + block_q - 1 + q_off)

    @pl.when(run)
    def _():
        # matmuls run in the INPUT dtype (bf16 on the MXU at full rate)
        # with f32 accumulation — an f32 upcast before the dot would halve
        # MXU throughput on the kernel's dominant FLOPs
        q = q_ref[0]                                     # (bq, d)
        k = k_ref[0]                                     # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk) f32
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_off
            ki = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(ki <= qi, logits, NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        if causal:
            p = jnp.where(logits > NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=-1)
        m_ref[:, 0] = m_new
        v = v_ref[0]                                     # (bk, d)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr[:, None] + pv

    @pl.when(j == nj - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        # log-sum-exp per query row: the backward kernels rebuild softmax
        # probabilities as exp(s - lse) without the online max recurrence
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(l)


def _pick_block(seq: int, target: int) -> Optional[int]:
    """Largest lane-aligned (multiple-of-128) block <= target dividing seq.

    Returns None when no such block exists (e.g. seq=100): Mosaic needs
    lane/sublane-aligned tiles, so the dispatcher must fall back to the
    blockwise jax path rather than hand Pallas an illegal block.
    """
    for b in range(min(target, seq), 127, -128):
        if seq % b == 0 and b % 128 == 0:
            return b
    return None


def flash_attention_tpu(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        block_q: int = 512, block_k: int = 512,
                        interpret: bool = False,
                        return_lse: bool = False):
    """Pallas flash-attention forward (TPU). No autodiff — use
    ``attention`` for a differentiable entry point.

    ``interpret=True`` runs the kernel in the Pallas interpreter (works on
    CPU) so the kernel body is testable without TPU hardware."""
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    n_rep = hq // hkv
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(skv, block_k)
    if block_q is None or block_k is None:
        raise ValueError(
            f"no lane-aligned block divides seq lengths ({sq}, {skv})")

    # (B, S, H, D) -> (B*H, S, D); kv head index = q head index // n_rep.
    qt = jnp.moveaxis(q, 2, 1).reshape(b * hq, sq, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * hkv, skv, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * hkv, skv, d)

    def kv_index(bh, i, j):
        hb = bh // hq  # batch
        h = bh % hq
        return (hb * hkv + h // n_rep, j, 0)

    grid = (b * hq, sq // block_q, skv // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_q=sq, seq_k=skv)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * hq, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = jnp.moveaxis(out.reshape(b, hq, sq, d), 1, 2)
    if return_lse:
        return out, lse.reshape(b, hq, sq)
    return out


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention backward: two kernels sharing the saved LSE
# (softmax is rebuilt as exp(s - lse), no online recurrence).
#   dQ kernel: grid (bh, q_blocks, kv_blocks), kv innermost, dq accumulated
#              in VMEM scratch across the kv loop.
#   dKV kernel: grid (bh, kv_blocks, q_blocks), q innermost, dk/dv
#               accumulated in scratch across the q loop.
# GQA: gradients come out at q-head granularity and are summed over each
# kv-head's group afterwards.
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                         dq_ref, dq_acc, *,
                         scale, causal, block_q, block_k, seq_q, seq_k):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_off = seq_k - seq_q
    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + q_off)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_off
            ki = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(ki <= qi, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = jax.lax.dot_general(do, v,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0][:, None])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == nj - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dvec_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          scale, causal, block_q, block_k, seq_q, seq_k):
    j = pl.program_id(1)   # kv block
    i = pl.program_id(2)   # q block (innermost)
    ni = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_off = seq_k - seq_q
    run = True
    if causal:
        # q block entirely above this kv block's diagonal → contributes 0
        run = (i * block_q + block_q - 1 + q_off) >= (j * block_k)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_off
            ki = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(ki <= qi, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])           # (bq, bk)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, d)
        dp = jax.lax.dot_general(do, v,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0][:, None])           # (bq, bk)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bk, d)

    @pl.when(i == ni - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def flash_attention_tpu_bwd(q, k, v, out, lse, do, *,
                            causal: bool = True,
                            scale: Optional[float] = None,
                            block_q: int = 512, block_k: int = 512,
                            interpret: bool = False):
    """Flash backward: (dq, dk, dv) from saved output + LSE."""
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    n_rep = hq // hkv
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(skv, block_k)
    if block_q is None or block_k is None:
        raise ValueError("no lane-aligned block for flash backward")

    qt = jnp.moveaxis(q, 2, 1).reshape(b * hq, sq, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * hkv, skv, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * hkv, skv, d)
    dot = jnp.moveaxis(do, 2, 1).reshape(b * hq, sq, d)
    lset = lse.reshape(b * hq, 1, sq)
    # D_i = rowsum(dO * O): the softmax-jacobian correction vector
    dvec = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1)                               # (b, sq, hq)
    dvec = jnp.moveaxis(dvec, 2, 1).reshape(b * hq, 1, sq)

    def kv_index(bh, i, j):
        hb = bh // hq
        h = bh % hq
        return (hb * hkv + h // n_rep, j, 0)

    def kv_index_jfirst(bh, j, i):
        hb = bh // hq
        h = bh % hq
        return (hb * hkv + h // n_rep, j, 0)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_q=sq, seq_k=skv)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * hq, sq // block_q, skv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lset, dvec)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_q=sq, seq_k=skv)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * hq, skv // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_k, d), kv_index_jfirst),
            pl.BlockSpec((1, block_k, d), kv_index_jfirst),
            pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, j, i: (bh, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda bh, j, i: (bh, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b * hq, skv, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(kt, vt, qt, dot, lset, dvec)

    dq = jnp.moveaxis(dq.reshape(b, hq, sq, d), 1, 2)
    # GQA: fold each kv head's q-head group gradients together
    dk = dk.reshape(b, hkv, n_rep, skv, d).sum(axis=2)
    dv = dv.reshape(b, hkv, n_rep, skv, d).sum(axis=2)
    dk = jnp.moveaxis(dk, 1, 2).astype(k.dtype)
    dv = jnp.moveaxis(dv, 1, 2).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Dispatcher with custom_vjp: pallas forward, pallas backward (blockwise
# fallback off-TPU / for unaligned shapes).
# ---------------------------------------------------------------------------


def _is_tpu_platform(name: str) -> bool:
    # "axon" is a relay PJRT backend fronting a real TPU chip.
    return name in ("tpu", "axon")


def _on_tpu(x) -> bool:
    """True when ``x`` lives on (or will be committed to) a TPU device."""
    try:
        devs = getattr(x, "devices", None)
        if callable(devs):
            ds = devs()
            if ds:
                return all(_is_tpu_platform(d.platform) for d in ds)
        return _is_tpu_platform(jax.default_backend())
    except Exception:  # pragma: no cover — tracers without devices
        return _is_tpu_platform(jax.default_backend())


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _attention_tpu(q, k, v, causal, scale):
    return flash_attention_tpu(q, k, v, causal=causal, scale=scale)


def _attn_fwd(q, k, v, causal, scale):
    out, lse = flash_attention_tpu(q, k, v, causal=causal, scale=scale,
                                   return_lse=True)
    return out, (q, k, v, out, lse)


def _attn_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    return flash_attention_tpu_bwd(q, k, v, out, lse, g,
                                   causal=causal, scale=scale)


_attention_tpu.defvjp(_attn_fwd, _attn_bwd)


def attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
              use_pallas: Optional[bool] = None):
    """Differentiable attention with TPU pallas fast path."""
    if use_pallas is None:
        sq, skv = q.shape[1], k.shape[1]
        use_pallas = (_on_tpu(q) and q.shape[-1] % 128 == 0
                      and _pick_block(sq, 512) is not None
                      and _pick_block(skv, 512) is not None)
    if use_pallas:
        return _attention_tpu(q, k, v, causal, scale)
    return blockwise_attention(q, k, v, causal=causal, scale=scale)
