"""Paged-attention decode kernel (Pallas/TPU).

The serving engine's decode attends over a paged KV cache: each
sequence's context lives in non-contiguous pages indexed by a block
table (ray_tpu/llm/cache.py). The XLA fallback gathers the pages into a
contiguous [B, S, kvh, hd] copy per burst (`jnp.take`) — at long
contexts that copy dominates HBM traffic. This kernel instead streams
pages straight from the cache pool guided by a scalar-prefetched block
table (the grid's page dimension DMAs exactly the pages each sequence
owns), with flash-style online softmax — no materialized gather.

Reference analog: the vLLM paged-attention CUDA kernels behind
ray.llm's vllm_engine (SURVEY §2.4) — rebuilt Pallas-native, since the
reference delegates all device work to vLLM.

Layout contract (matches llm/cache.py):
  cache_k/cache_v (one layer): [P, page, kvh, hd]
  block_tables:                [B, max_pages] int32 (page 0 = dump page)
  q:                           [B, kvh, rep, hd]   (rep = heads per kv head)
  new_k/new_v:                 [B, K, kvh, hd]     burst scratch (in-VMEM tail)
  ctx_len:                     [B] int32           valid OLD positions
  new_len:                     [B] int32           valid NEW (burst) positions

Grid: (B, kvh, n_pages + 1). Page steps accumulate (m, l, acc) in VMEM
scratch; the final step folds in the burst tail and writes the
normalized output. Masking: page p covers absolute positions
[p*page_size, ...); rows >= ctx_len[b] are masked; the dump page
(table entry 0 for unused slots) masks out naturally the same way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - exercised on TPU builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None
else:
    if not hasattr(pltpu, "CompilerParams"):
        # pre-rename jax spells it TPUCompilerParams
        pltpu.CompilerParams = pltpu.TPUCompilerParams

_NEG_INF = -1e30


def _kernel(ctx_len_ref, new_len_ref, bt_ref,  # scalar prefetch
            q_ref, k_page_ref, v_page_ref, new_k_ref, new_v_ref,
            o_ref,
            m_ref, l_ref, acc_ref,
            *, page_size: int, n_pages: int, scale: float, kvh: int):
    """Grid (B, n_pages + 1); blocks carry whole pages [page, kvh, hd]
    (TPU tiling: a block's trailing dims must equal the array's or tile
    by (8, 128) — the head dim therefore stays INSIDE the block and the
    kernel unrolls over the static kvh). Scratch rows are the kvh*rep
    flattened query heads."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    rep = q_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def online_update(g, k, v, pos_mask):
        """One flash block for kv head g: k/v [S, hd] f32, mask [S]."""
        rows = slice(g * rep, (g + 1) * rep)
        q = q_ref[0, g].astype(jnp.float32) * scale   # [rep, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [rep, S]
        s = jnp.where(pos_mask[None, :], s, _NEG_INF)
        m_prev = m_ref[rows]                          # [rep, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # masked entries must contribute EXACTLY zero: when a whole
        # block is masked, m_new == _NEG_INF and exp(s - m_new) would be
        # exp(0) = 1 per masked entry, poisoning l and acc
        p_blk = jnp.where(pos_mask[None, :],
                          jnp.exp(s - m_new), 0.0)    # [rep, S]
        l_ref[rows] = l_ref[rows] * alpha + p_blk.sum(-1, keepdims=True)
        acc_ref[rows] = acc_ref[rows] * alpha + jax.lax.dot_general(
            p_blk, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [rep, hd]
        m_ref[rows] = m_new

    @pl.when(p < n_pages)
    def _page_step():
        base = p * page_size
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)[:, 0]
        mask = pos < ctx_len_ref[b]
        for g in range(kvh):  # static unroll over kv heads
            k = k_page_ref[0, :, g].astype(jnp.float32)   # [page, hd]
            v = v_page_ref[0, :, g].astype(jnp.float32)
            online_update(g, k, v, mask)

    @pl.when(p == n_pages)
    def _tail_and_write():
        kk = new_k_ref.shape[1]
        pos = jax.lax.broadcasted_iota(jnp.int32, (kk, 1), 0)[:, 0]
        mask = pos < new_len_ref[b]
        for g in range(kvh):
            k = new_k_ref[0, :, g].astype(jnp.float32)    # [K, hd]
            v = new_v_ref[0, :, g].astype(jnp.float32)
            online_update(g, k, v, mask)
        l = jnp.maximum(l_ref[...], 1e-20)
        out = (acc_ref[...] / l)                      # [kvh*rep, hd]
        o_ref[0] = out.reshape(kvh, rep, out.shape[-1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention(q, cache_k, cache_v, new_k, new_v,
                           block_tables, ctx_len, new_len, *,
                           page_size: int, interpret: bool = False):
    """Decode attention over paged KV + an in-flight burst tail.

    q [B, kvh, rep, hd]; cache_k/cache_v [P, page, kvh, hd];
    new_k/new_v [B, K, kvh, hd]; block_tables [B, n_pages] int32;
    ctx_len/new_len [B] int32. Returns o [B, kvh, rep, hd] (q dtype).
    """
    if pltpu is None:
        raise RuntimeError("pallas TPU backend unavailable")
    if jax.default_backend() == "cpu":
        interpret = True  # CPU tests run the kernel body via interpreter
    B, kvh, rep, hd = q.shape
    n_pages = block_tables.shape[1]
    K = new_k.shape[1]
    grid = (B, n_pages + 1)

    def q_map(b, p, ctx, nl, bt):
        return (b, 0, 0, 0)

    def page_map(b, p, ctx, nl, bt):
        # last (tail) step re-reads an arbitrary valid page; masked out
        return (bt[b, jnp.minimum(p, n_pages - 1)], 0, 0, 0)

    def new_map(b, p, ctx, nl, bt):
        return (b, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, kvh, rep, hd), q_map),
            pl.BlockSpec((1, page_size, kvh, hd), page_map),
            pl.BlockSpec((1, page_size, kvh, hd), page_map),
            pl.BlockSpec((1, K, kvh, hd), new_map),
            pl.BlockSpec((1, K, kvh, hd), new_map),
        ],
        out_specs=pl.BlockSpec((1, kvh, rep, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((kvh * rep, 1), jnp.float32),   # m
            pltpu.VMEM((kvh * rep, 1), jnp.float32),   # l
            pltpu.VMEM((kvh * rep, hd), jnp.float32),  # acc
        ],
    )
    kernel = functools.partial(
        _kernel, page_size=page_size, n_pages=n_pages,
        scale=float(hd) ** -0.5, kvh=kvh)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, kvh, rep, hd), q.dtype),
        interpret=interpret,
        # the batch dim is parallel; the page dim carries the softmax
        # state and must run sequentially
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(ctx_len, new_len, block_tables, q, cache_k, cache_v, new_k, new_v)


def paged_decode_attention_reference(q, cache_k, cache_v, new_k, new_v,
                                     block_tables, ctx_len, new_len):
    """Naive oracle: gather pages, mask, softmax (the XLA-path shape)."""
    B, kvh, rep, hd = q.shape
    page = cache_k.shape[1]
    Sold = block_tables.shape[1] * page
    ok = jnp.take(cache_k, block_tables, axis=0).reshape(B, Sold, kvh, hd)
    ov = jnp.take(cache_v, block_tables, axis=0).reshape(B, Sold, kvh, hd)
    scale = hd ** -0.5
    s_old = jnp.einsum("bgrd,bsgd->bgrs", q.astype(jnp.float32),
                       ok.astype(jnp.float32)) * scale
    s_new = jnp.einsum("bgrd,bkgd->bgrk", q.astype(jnp.float32),
                       new_k.astype(jnp.float32)) * scale
    old_mask = jnp.arange(Sold)[None, :] < ctx_len[:, None]
    new_mask = jnp.arange(new_k.shape[1])[None, :] < new_len[:, None]
    s_old = jnp.where(old_mask[:, None, None, :], s_old, _NEG_INF)
    s_new = jnp.where(new_mask[:, None, None, :], s_new, _NEG_INF)
    s = jnp.concatenate([s_old, s_new], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    o = (jnp.einsum("bgrs,bsgd->bgrd", p[..., :Sold],
                    ov.astype(jnp.float32))
         + jnp.einsum("bgrk,bkgd->bgrd", p[..., Sold:],
                      new_v.astype(jnp.float32)))
    return o.astype(q.dtype)
