"""Rotary position embeddings (RoPE), Llama-3 style.

Frequencies are precomputed once per model (host side) and passed in as a
(seq, head_dim/2) cos/sin table so the per-step work is one fused
elementwise multiply on the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0,
                     dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables of shape (max_seq, head_dim // 2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin, positions=None):
    """Rotate half-split pairs (x[..., :d/2], x[..., d/2:]) by the
    position angle — the GPT-NeoX / HF-Llama layout. Checkpoints stored
    in Meta's interleaved even/odd layout must be permuted at load time
    (handled by the model's checkpoint import, not here).

    x: (..., seq, heads, head_dim). cos/sin: (max_seq, head_dim//2) or
    already gathered (..., seq, head_dim//2) when ``positions`` is given
    (decode path with per-sequence offsets).
    """
    if positions is not None:
        cos = jnp.take(cos, positions, axis=0)
        sin = jnp.take(sin, positions, axis=0)
    else:
        seq = x.shape[-3]
        cos, sin = cos[:seq], sin[:seq]
    # broadcast over heads: (..., seq, 1, head_dim//2)
    cos = jnp.expand_dims(cos, axis=-2)
    sin = jnp.expand_dims(sin, axis=-2)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
