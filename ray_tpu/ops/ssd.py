"""SSD: the Mamba-2 state-space op in chunked (matmul) form.

State-space duality (Dao & Gu 2024) rewrites the selective-scan
recurrence

    h_t = a_t * h_{t-1} + (dt_t x_t) outer B_t
    y_t = C_t . h_t + D * x_t          (a_t = exp(dt_t * A), A < 0)

as chunked matmuls: within a chunk the output is an attention-like
product (C B^T masked by the 1-semiseparable decay L), and chunks
exchange only a (head_dim x state) state through a short lax.scan.
That is the TPU-first form — the FLOPs land in einsums the MXU tiles
natively, and the sequential dependency shrinks from seq to seq/chunk.
``ssd_reference`` is the literal recurrence, kept as the test oracle.

Shapes (B=batch, S=seq, H=heads, P=head_dim, N=state):
    x: (B, S, H, P)   dt: (B, S, H)   A: (H,)
    Bm/Cm: (B, S, H, N)   D: (H,)   -> y: (B, S, H, P)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ssd_chunked", "ssd_reference"]


def ssd_reference(x, dt, A, Bm, Cm, D):
    """Sequential recurrence oracle (f32)."""
    xf = x.astype(jnp.float32)
    dtf = dt.astype(jnp.float32)
    a = jnp.exp(dtf * A.astype(jnp.float32))          # (B, S, H)
    Bf = Bm.astype(jnp.float32)
    Cf = Cm.astype(jnp.float32)

    def step(h, inp):
        a_t, bx_t, c_t = inp
        h = a_t[..., None, None] * h + bx_t
        y = jnp.einsum("bhn,bhpn->bhp", c_t, h)
        return h, y

    bx = jnp.einsum("bsh,bshp,bshn->bshpn", dtf, xf, Bf)
    B_, S, H, P = x.shape
    N = Bm.shape[-1]
    h0 = jnp.zeros((B_, H, P, N), jnp.float32)
    _, ys = jax.lax.scan(
        step, h0,
        (jnp.moveaxis(a, 1, 0), jnp.moveaxis(bx, 1, 0),
         jnp.moveaxis(Cf, 1, 0)))
    y = jnp.moveaxis(ys, 0, 1)
    return (y + xf * D.astype(jnp.float32)[None, None, :, None]
            ).astype(x.dtype)


def ssd_chunked(x, dt, A, Bm, Cm, D, chunk: int = 64):
    """Chunked SSD. seq must be a multiple of `chunk` (pad upstream).

    Bm/Cm may be head-shared — shape (B, S, 1, N) — in which case the
    C.B^T score and state contractions compute ONCE and broadcast over
    heads (materializing the repeat would multiply those einsums' FLOPs
    by H for identical results)."""
    B_, S, H, P = x.shape
    N = Bm.shape[-1]
    if S % chunk:
        raise ValueError(f"seq {S} not a multiple of chunk {chunk}")
    C_ = S // chunk
    f32 = jnp.float32
    shared_bc = Bm.shape[2] == 1 and Cm.shape[2] == 1 and H > 1
    xf = x.astype(f32).reshape(B_, C_, chunk, H, P)
    dtf = dt.astype(f32).reshape(B_, C_, chunk, H)
    Hbc = 1 if shared_bc else H
    Bf = Bm.astype(f32).reshape(B_, C_, chunk, Hbc, N)
    Cf = Cm.astype(f32).reshape(B_, C_, chunk, Hbc, N)

    # log-decay cumulative within each chunk (inclusive of own step)
    log_a = dtf * A.astype(f32)                       # (B, C, Q, H)
    cum = jnp.cumsum(log_a, axis=2)
    # L[i, j] = exp(cum[i] - cum[j]) for j <= i (decay j+1..i).
    # cum is decreasing (A < 0), so every CAUSAL entry has exponent
    # <= 0 — the clamp is exact there and exists purely to keep the
    # anti-causal branch finite: where() still evaluates it, and its
    # overflowing exp turns into inf*0 = NaN in the BACKWARD pass (the
    # classic where-grad trap; seen as grad_norm=nan at step 0 on the
    # 130m config).
    li = cum[:, :, :, None, :]                        # (B, C, Q, 1, H)
    lj = cum[:, :, None, :, :]                        # (B, C, 1, Q, H)
    Q = chunk
    causal = jnp.tril(jnp.ones((Q, Q), bool))[None, None, :, :, None]
    L = jnp.where(causal, jnp.exp(jnp.minimum(li - lj, 0.0)), 0.0)

    dx = dtf[..., None] * xf                          # (B, C, Q, H, P)
    # intra-chunk: (C_i . B_j) * L[i,j] applied to dx_j
    if shared_bc:
        inner = jnp.einsum("bcin,bcjn->bcij",
                           Cf[:, :, :, 0], Bf[:, :, :, 0])
        scores = inner[..., None] * L                 # broadcast over H
    else:
        scores = jnp.einsum("bcihn,bcjhn->bcijh", Cf, Bf) * L
    y_intra = jnp.einsum("bcijh,bcjhp->bcihp", scores, dx)

    # per-chunk aggregate state + total decay
    last = cum[:, :, -1:, :]                          # (B, C, 1, H)
    decay_to_end = jnp.exp(last - cum)                # (B, C, Q, H)
    if shared_bc:
        S_c = jnp.einsum("bcjh,bcjhp,bcjn->bchpn",
                         decay_to_end, dx, Bf[:, :, :, 0])
    else:
        S_c = jnp.einsum("bcjh,bcjhp,bcjhn->bchpn", decay_to_end, dx, Bf)
    chunk_decay = jnp.exp(last[:, :, 0, :])           # (B, C, H)

    # inter-chunk: H_c = chunk_decay_c * H_{c-1} + S_c (scan over C_)
    def step(h, inp):
        dec, s = inp
        h_prev = h
        h = dec[..., None, None] * h + s
        return h, h_prev

    h0 = jnp.zeros((B_, H, P, N), f32)
    _, h_prevs = jax.lax.scan(
        step, h0,
        (jnp.moveaxis(chunk_decay, 1, 0), jnp.moveaxis(S_c, 1, 0)))
    h_prev = jnp.moveaxis(h_prevs, 0, 1)              # (B, C, H, P, N)

    # carry-in contribution at position i: exp(cum[i]) * C_i . H_{c-1}
    if shared_bc:
        y_inter = jnp.einsum("bcih,bcin,bchpn->bcihp",
                             jnp.exp(cum), Cf[:, :, :, 0], h_prev)
    else:
        y_inter = jnp.einsum("bcih,bcihn,bchpn->bcihp",
                             jnp.exp(cum), Cf, h_prev)

    y = (y_intra + y_inter).reshape(B_, S, H, P)
    return (y + x.astype(f32) * D.astype(f32)[None, None, :, None]
            ).astype(x.dtype)
