"""In-process multi-node test cluster.

TPU-native analog of the reference test backbone (ref:
python/ray/cluster_utils.py — Cluster:135, add_node:202): multiple raylets
with spoofed resource capacities run inside one process, each with its own
node id, object-store namespace, and RPC endpoint, against one real GCS.
Worker processes are real subprocesses; scheduling, spillback, placement
groups, and inter-node object transfer exercise the same code paths a
physical multi-host deployment does.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ._private.node import Node


class Cluster:
    """A head node plus on-demand worker nodes, all driven in-process.

    ``tcp=True`` binds the GCS and every raylet on TCP loopback ports instead
    of unix sockets — the cross-host (DCN) transport path.
    """

    def __init__(
        self,
        initialize_head: bool = True,
        connect: bool = False,
        head_node_args: Optional[Dict] = None,
        tcp: bool = False,
    ):
        self.head_node: Optional[Node] = None
        self.worker_nodes: List[Node] = []
        self._connected = False
        if initialize_head:
            args = dict(head_node_args or {})
            res = dict(args.pop("resources", None) or {})
            if "num_cpus" in args:
                res["CPU"] = float(args.pop("num_cpus"))
            if "num_tpus" in args:
                res["TPU"] = float(args.pop("num_tpus"))
            if res:
                args["resources"] = res
            if tcp:
                args.setdefault("port", 0)
            self.head_node = Node(head=True, **args)
            self.head_node.start()
            if connect:
                self.connect()

    @property
    def address(self) -> str:
        return self.head_node.gcs_address

    def connect(self):
        """Attach the calling process as the driver of this cluster."""
        from . import _worker_api

        _worker_api._connect_to_node(self.head_node)
        self._connected = True

    def add_node(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
        **res_kwargs,
    ) -> Node:
        """Start a worker node. ``num_cpus=N`` / ``num_tpus=N`` shorthands
        mirror the reference add_node signature."""
        res = dict(resources or {})
        if "num_cpus" in res_kwargs:
            res["CPU"] = float(res_kwargs.pop("num_cpus"))
        if "num_tpus" in res_kwargs:
            res["TPU"] = float(res_kwargs.pop("num_tpus"))
        if res_kwargs:
            raise TypeError(f"unknown add_node args: {sorted(res_kwargs)}")
        res.setdefault("CPU", 1.0)
        node = Node(
            head=False,
            session_name=self.head_node.session_name,
            gcs_address=self.head_node.gcs_address,
            resources=res,
            labels=labels,
            object_store_memory=object_store_memory,
        )
        node.start()
        self.worker_nodes.append(node)
        return node

    def remove_node(self, node: Node, allow_graceful: bool = False):
        """Take a node down. Default is abrupt death (SIGKILL workers, dropped
        connections) so failure-detection paths are exercised; pass
        ``allow_graceful=True`` for a clean drain."""
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)
        if allow_graceful:
            node.stop()
        else:
            node.die()

    def shutdown(self):
        from . import _worker_api

        if self._connected:
            _worker_api.shutdown()
            self.head_node = None  # stopped by the driver shutdown
        for node in list(self.worker_nodes):
            try:
                node.stop()
            except Exception:
                pass
        self.worker_nodes.clear()
        if self.head_node is not None:
            self.head_node.stop()
            self.head_node = None
