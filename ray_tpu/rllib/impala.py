"""IMPALA: asynchronous sampling + V-trace off-policy correction.

Reference analog: rllib/algorithms/impala/ (the async Learner stack).
Rebuilt TPU-first: the whole V-trace + policy-gradient update is ONE
jitted function; asynchrony comes from the task plane — every EnvRunner
actor keeps a sample() in flight, the learner consumes fragments as
ray_tpu.wait surfaces them and pushes fresh weights only to the runner
it just drained, so slow actors never gate fast ones (the architecture's
point; Espeholt et al. 2018 defines the v-trace targets used here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointableAlgorithm
from .env import make_env
from .ppo import EnvRunner, init_policy, policy_forward

_IMPALA_UPDATE_JIT = None


def impala_update(params, opt_state, batch, lr, *, gamma: float,
                  vf_coef: float, ent_coef: float, rho_bar: float,
                  c_bar: float, clip_param: float = 0.0):
    global _IMPALA_UPDATE_JIT
    if _IMPALA_UPDATE_JIT is None:
        import jax

        _IMPALA_UPDATE_JIT = jax.jit(
            _impala_update_impl,
            static_argnames=("gamma", "vf_coef", "ent_coef", "rho_bar",
                             "c_bar", "clip_param"))
    return _IMPALA_UPDATE_JIT(params, opt_state, batch, lr, gamma=gamma,
                              vf_coef=vf_coef, ent_coef=ent_coef,
                              rho_bar=rho_bar, c_bar=c_bar,
                              clip_param=clip_param)


def _impala_update_impl(params, opt_state, batch, lr, *, gamma: float,
                        vf_coef: float, ent_coef: float, rho_bar: float,
                        c_bar: float, clip_param: float = 0.0):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)

    def loss_fn(p):
        logits, values = policy_forward(p, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1)[:, 0]
        # importance ratios vs the BEHAVIOR policy that sampled
        rhos = jnp.exp(logp - batch["logp"])
        clipped_rho = jnp.minimum(rho_bar, rhos)
        clipped_c = jnp.minimum(c_bar, rhos)
        nonterminal = 1.0 - batch["dones"]
        values_next = jnp.concatenate(
            [values[1:], batch["bootstrap_value"][None]])
        # v-trace: vs_t = V_t + delta_t + gamma c_t (vs_{t+1} - V_{t+1}),
        # swept right-to-left (stop-gradient through targets)
        v = jax.lax.stop_gradient(values)
        v_next = jax.lax.stop_gradient(values_next)
        deltas = clipped_rho * (
            batch["rewards"] + gamma * nonterminal * v_next - v)

        def scan_fn(carry, inp):
            delta, c, nt, v_nx = inp
            acc = delta + gamma * nt * c * carry
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn, jnp.float32(0.0),
            (deltas, clipped_c, nonterminal, v_next), reverse=True)
        vs = v + vs_minus_v
        vs_next = jnp.concatenate([vs[1:], v_next[-1:]])
        pg_adv = clipped_rho * (
            batch["rewards"] + gamma * nonterminal * vs_next - v)
        adv = jax.lax.stop_gradient(pg_adv)
        if clip_param > 0.0:
            # APPO (ref: rllib/algorithms/appo/): PPO's clipped
            # surrogate on the v-trace advantages — async sampling with
            # bounded policy steps per update
            surr = jnp.minimum(
                rhos * adv,
                jnp.clip(rhos, 1.0 - clip_param, 1.0 + clip_param) * adv)
            pi_loss = -surr.mean()
        else:
            pi_loss = -(adv * logp).mean()
        vf_loss = jnp.square(values - jax.lax.stop_gradient(vs)).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coef * vf_loss - ent_coef * entropy
        return total, (pi_loss, vf_loss, entropy, rhos.mean())

    (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, {
        "total_loss": loss, "policy_loss": aux[0], "vf_loss": aux[1],
        "entropy": aux[2], "mean_rho": aux[3]}


@dataclass
class IMPALAConfig:
    env: Any = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    lr: float = 6e-4
    gamma: float = 0.99
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    # how many fragments one train() call consumes (each triggers an
    # update — IMPALA updates per-fragment, not per-epoch)
    fragments_per_iter: int = 4
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0
    # 0 = plain v-trace policy gradient (IMPALA); >0 = PPO clipped
    # surrogate on the v-trace advantages (APPO)
    clip_param: float = 0.0

    def environment(self, env) -> "IMPALAConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "IMPALAConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "IMPALAConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA(CheckpointableAlgorithm):
    """Async actor-learner: one sample() stays in flight per runner;
    fragments are consumed in completion order (ray_tpu.wait), each
    immediately updating the learner and refreshing only the drained
    runner's weights."""

    def __init__(self, config: IMPALAConfig):
        import jax
        import optax

        import ray_tpu

        self.config = config
        probe = make_env(config.env, seed=0)
        self.obs_dim = probe.observation_dim
        self.act_dim = probe.action_dim
        self.params = init_policy(jax.random.PRNGKey(config.seed),
                                  self.obs_dim, self.act_dim,
                                  config.hidden)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.iteration = 0

        runner_cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.hidden,
                              config.seed + 100 + i)
            for i in range(config.num_env_runners)
        ]
        self._inflight: Dict[Any, Any] = {}  # ref -> runner
        from .checkpoint import broadcast_suppressed

        if not broadcast_suppressed():
            self._broadcast_all()

    def _host_params(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def _broadcast_all(self) -> None:
        import ray_tpu

        ray_tpu.get([r.set_params.remote(self._host_params())
                     for r in self.runners], timeout=120)

    def _launch(self, runner) -> None:
        ref = runner.sample.remote(self.config.rollout_fragment_length)
        self._inflight[ref] = runner

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        import ray_tpu

        cfg = self.config
        for runner in self.runners:
            if runner not in self._inflight.values():
                self._launch(runner)
        losses: Dict[str, float] = {}
        ep_returns: list = []
        consumed = 0
        while consumed < cfg.fragments_per_iter:
            ready, _ = ray_tpu.wait(list(self._inflight),
                                    num_returns=1, timeout=300)
            if not ready:
                raise TimeoutError("no fragment arrived within 300 s")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            frag = ray_tpu.get(ref)
            batch = {
                "obs": jnp.asarray(frag["obs"]),
                "actions": jnp.asarray(frag["actions"]),
                "rewards": jnp.asarray(frag["rewards"]),
                "dones": jnp.asarray(frag["dones"]),
                "logp": jnp.asarray(frag["logp"]),
                "bootstrap_value": jnp.asarray(frag["bootstrap_value"]),
            }
            self.params, self.opt_state, losses = impala_update(
                self.params, self.opt_state, batch, cfg.lr,
                gamma=cfg.gamma, vf_coef=cfg.vf_loss_coeff,
                ent_coef=cfg.entropy_coeff,
                rho_bar=cfg.vtrace_rho_clip, c_bar=cfg.vtrace_c_clip,
                clip_param=cfg.clip_param)
            ep_returns.extend(frag["episode_returns"].tolist())
            # fresh weights to the runner we just drained, then relaunch
            ray_tpu.get(runner.set_params.remote(self._host_params()),
                        timeout=60)
            self._launch(runner)
            consumed += 1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(ep_returns))
                                    if ep_returns else float("nan")),
            "episodes_this_iter": len(ep_returns),
            "timesteps_this_iter": consumed * cfg.rollout_fragment_length,
            **{k: float(v) for k, v in losses.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        for runner in self.runners:
            try:
                ray_tpu.kill(runner)
            except Exception:
                pass
        self.runners = []
        self._inflight.clear()
