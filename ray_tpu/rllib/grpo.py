"""GRPO: group-relative policy optimization for LLM RLHF.

The RLHF path named in BASELINE.json ("PPO / GRPO RLHF with Ray-RLlib on
TPU"). GRPO (Shao et al. 2024, DeepSeekMath) removes PPO's value network:
G completions are sampled per prompt, and each completion's advantage is
its reward standardized WITHIN its group — the group mean is the
baseline. The update is a token-level policy gradient on completion
tokens plus a KL penalty to the frozen reference policy (the k3
estimator, Schulman 2020), all in one jitted function.

The policy is the Llama family itself (models/llama.py) — the same
params train.make_train_step pretrains and llm.LLMEngine serves, so RLHF
composes with the rest of the stack instead of living beside it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..models import LLAMA_CONFIGS, forward, init_params
from ..models.llama import LlamaConfig

__all__ = ["GRPO", "GRPOConfig"]

_UPDATE_JIT = {}
_SAMPLE_FWD_JIT = {}


def _forward_jit(cfg: LlamaConfig):
    """Per-config jitted forward (LlamaConfig is a frozen dataclass —
    hashable — so the config itself is the cache key; a fresh lambda per
    call would retrace and recompile every sampling step)."""
    fn = _SAMPLE_FWD_JIT.get(cfg)
    if fn is None:
        import jax

        fn = _SAMPLE_FWD_JIT[cfg] = jax.jit(
            lambda p, t: forward(p, t, cfg))
    return fn


@dataclass
class GRPOConfig:
    model: str = "tiny"               # LLAMA_CONFIGS key or cfg via .llama_config
    llama_config: Optional[LlamaConfig] = None
    group_size: int = 8               # completions per prompt (G)
    max_prompt_len: int = 16
    max_tokens: int = 16              # completion budget
    temperature: float = 1.0
    lr: float = 1e-4
    kl_coef: float = 0.02
    adv_clip: float = 5.0
    seed: int = 0

    def training(self, **kwargs) -> "GRPOConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self, params=None) -> "GRPO":
        return GRPO(self, params=params)


def _sample_group(params, cfg: LlamaConfig, prompt: Sequence[int],
                  group: int, max_tokens: int, temperature: float,
                  key):
    """G sampled continuations of one prompt -> (tokens[G, P+T],
    completion_mask[G, P+T]). Greedy when temperature == 0."""
    import jax
    import jax.numpy as jnp

    plen = len(prompt)
    total = plen + max_tokens
    tokens = jnp.tile(jnp.asarray(prompt, jnp.int32)[None, :], (group, 1))
    tokens = jnp.pad(tokens, ((0, 0), (0, max_tokens)))

    fwd = _forward_jit(cfg)
    for t in range(plen, total):
        logits = fwd(params, tokens)[:, t - 1, :]
        if temperature == 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(
                sub, logits / temperature, axis=-1).astype(jnp.int32)
        tokens = tokens.at[:, t].set(nxt)
    mask = jnp.zeros((group, total), jnp.float32).at[:, plen:].set(1.0)
    return np.asarray(tokens), np.asarray(mask)


def _grpo_update(params, ref_params, opt_state, batch, lr, *,
                 kl_coef: float, cfg: LlamaConfig):
    # keyed on the FULL (frozen, hashable) config: a name- or
    # shape-derived key would collide for distinct custom configs and
    # silently run the wrong architecture's closed-over cfg
    fn = _UPDATE_JIT.get(cfg)
    if fn is None:
        import jax

        fn = _UPDATE_JIT[cfg] = jax.jit(
            lambda p, rp, o, b, lr_, kl: _grpo_impl(
                p, rp, o, b, lr_, kl, cfg))
    return fn(params, ref_params, opt_state, batch, lr, kl_coef)


def _grpo_impl(params, ref_params, opt_state, batch, lr, kl_coef, cfg):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)

    def token_logp(p, tokens):
        logits = forward(p, tokens, cfg).astype(jnp.float32)
        logp_all = jax.nn.log_softmax(logits[:, :-1, :])
        return jnp.take_along_axis(
            logp_all, tokens[:, 1:, None], axis=-1)[..., 0]

    def loss_fn(p):
        tokens = batch["tokens"]
        mask = batch["mask"][:, 1:]            # predicts token t from t-1
        logp = token_logp(p, tokens)
        ref_logp = jax.lax.stop_gradient(token_logp(ref_params, tokens))
        adv = batch["advantages"][:, None]     # per-sequence, broadcast
        denom = mask.sum() + 1e-8
        pg = -(adv * logp * mask).sum() / denom
        # k3 KL estimator: e^(ref-pi) - (ref-pi) - 1 >= 0, low variance
        diff = ref_logp - logp
        kl = ((jnp.exp(diff) - diff - 1.0) * mask).sum() / denom
        total = pg + kl_coef * kl
        return total, (pg, kl, (logp * mask).sum() / denom)

    (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, {"total_loss": loss, "pg_loss": aux[0],
                               "kl": aux[1], "mean_logp": aux[2]}


class GRPO:
    """train(prompts, reward_fn) — one GRPO iteration: sample G
    completions per prompt, group-standardize rewards, policy-gradient
    update with reference-KL."""

    def __init__(self, config: GRPOConfig, params=None):
        import jax
        import optax

        self.config = config
        self.cfg = config.llama_config or LLAMA_CONFIGS[config.model]
        if params is None:
            params = init_params(jax.random.PRNGKey(config.seed), self.cfg)
        self.params = params
        # the frozen reference policy the KL tethers to
        self.ref_params = jax.tree.map(lambda x: x, params)
        self.opt_state = optax.adam(config.lr).init(params)
        self._key = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0

    def train(self, prompts: Sequence[Sequence[int]],
              reward_fn: Callable[[List[List[int]]], Sequence[float]]
              ) -> Dict[str, Any]:
        """reward_fn receives the COMPLETION token lists (prompt
        stripped) for all groups flattened, returns one float each."""
        import jax
        import jax.numpy as jnp

        cfg, ccfg = self.cfg, self.config
        all_tokens, all_masks, all_advs, all_rewards = [], [], [], []
        for prompt in prompts:
            prompt = list(prompt)[: ccfg.max_prompt_len]
            self._key, sub = jax.random.split(self._key)
            tokens, mask = _sample_group(
                self.params, cfg, prompt, ccfg.group_size,
                ccfg.max_tokens, ccfg.temperature, sub)
            completions = [row[len(prompt):].tolist() for row in tokens]
            rewards = np.asarray(reward_fn(completions), np.float32)
            # group-relative: the group's mean IS the baseline
            adv = (rewards - rewards.mean()) / (rewards.std() + 1e-6)
            adv = np.clip(adv, -ccfg.adv_clip, ccfg.adv_clip)
            all_tokens.append(tokens)
            all_masks.append(mask)
            all_advs.append(adv)
            all_rewards.extend(rewards.tolist())
        # mixed prompt lengths: right-pad every group to the longest
        # total. Pads sit AFTER each row's completion, so causal
        # attention never lets them influence scored positions, and the
        # mask (0 on pads) excludes them from the loss.
        width = max(t.shape[1] for t in all_tokens)
        all_tokens = [np.pad(t, ((0, 0), (0, width - t.shape[1])))
                      for t in all_tokens]
        all_masks = [np.pad(m, ((0, 0), (0, width - m.shape[1])))
                     for m in all_masks]
        batch = {
            "tokens": jnp.asarray(np.concatenate(all_tokens)),
            "mask": jnp.asarray(np.concatenate(all_masks)),
            "advantages": jnp.asarray(np.concatenate(all_advs)),
        }
        self.params, self.opt_state, losses = _grpo_update(
            self.params, self.ref_params, self.opt_state, batch,
            ccfg.lr, kl_coef=ccfg.kl_coef, cfg=cfg)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "reward_mean": float(np.mean(all_rewards)),
            "reward_std": float(np.std(all_rewards)),
            "num_completions": len(all_rewards),
            **{k: float(v) for k, v in losses.items()},
        }
