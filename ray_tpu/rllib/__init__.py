"""ray_tpu.rllib: RL training library (ref: rllib/ — new API stack:
EnvRunner sampling actors + a jitted jax Learner; SURVEY §2.4)."""

from .env import CartPole, make_env
from .ppo import PPO, PPOConfig, EnvRunner

__all__ = ["PPO", "PPOConfig", "EnvRunner", "CartPole", "make_env"]
