"""ray_tpu.rllib: RL training library (ref: rllib/ — new API stack:
EnvRunner sampling actors + a jitted jax Learner; SURVEY §2.4)."""

from .env import CartPole, make_env
from .dqn import DQN, DQNConfig
from .ppo import PPO, PPOConfig, EnvRunner

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "EnvRunner",
           "CartPole", "make_env"]
