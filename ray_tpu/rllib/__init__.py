"""ray_tpu.rllib: RL training library (ref: rllib/ — new API stack:
EnvRunner sampling actors + a jitted jax Learner; SURVEY §2.4).

Algorithm families: PPO (on-policy), IMPALA (async + v-trace), DQN
(off-policy value), BC/MARWIL (offline), GRPO (LLM RLHF)."""

from .env import CartPole, make_env
from .dqn import DQN, DQNConfig
from .grpo import GRPO, GRPOConfig
from .impala import IMPALA, IMPALAConfig
from .appo import APPO, APPOConfig
from .offline import (BC, BCConfig, MARWIL, MARWILConfig,
                      record_rollouts, rollout_dataset)
from .ppo import PPO, PPOConfig, EnvRunner
from .sac import SAC, SACConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "SAC",
           "SACConfig", "IMPALA", "APPO", "APPOConfig",
           "IMPALAConfig", "BC", "BCConfig", "MARWIL", "MARWILConfig",
           "GRPO", "GRPOConfig", "EnvRunner", "CartPole", "make_env",
           "record_rollouts", "rollout_dataset"]
