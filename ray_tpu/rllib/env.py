"""Built-in environments (ref: rllib's env layer; gym is not a baked-in
dependency, so the classic control task used by the smoke tests is
implemented directly — standard CartPole dynamics).

API mirrors gymnasium: reset() -> (obs, info), step(a) ->
(obs, reward, terminated, truncated, info).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np


class CartPole:
    """Classic cart-pole balancing (the CartPole-v1 task: physics per
    Barto, Sutton & Anderson 1983; episode caps at 500 steps)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * math.pi / 180
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_dim = 4
    action_dim = 2

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros(4, np.float64)
        self.steps = 0

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self.steps = 0
        return self.state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = math.cos(theta), math.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.steps += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self.steps >= self.MAX_STEPS
        return (self.state.astype(np.float32), 1.0, terminated, truncated,
                {})


class Pendulum:
    """Classic pendulum swing-up (the Pendulum-v1 task: torque-limited
    continuous control; reward = -(theta² + 0.1·theta_dot² + 0.001·u²)).
    The continuous-action counterpart to the discrete CartPole — SAC's
    native habitat."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    observation_dim = 3
    action_dim = 1          # continuous: u in [-MAX_TORQUE, MAX_TORQUE]
    continuous = True

    def __init__(self, seed: Optional[int] = None):
        self.rng = np.random.default_rng(seed)
        self.theta = 0.0
        self.theta_dot = 0.0
        self.steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([math.cos(self.theta), math.sin(self.theta),
                         self.theta_dot], np.float32)

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.theta = self.rng.uniform(-math.pi, math.pi)
        self.theta_dot = self.rng.uniform(-1.0, 1.0)
        self.steps = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th, thd = self.theta, self.theta_dot
        norm_th = ((th + math.pi) % (2 * math.pi)) - math.pi
        cost = norm_th ** 2 + 0.1 * thd ** 2 + 0.001 * u ** 2
        thd = thd + (3 * self.G / (2 * self.L) * math.sin(th)
                     + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        thd = float(np.clip(thd, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + thd * self.DT
        self.theta, self.theta_dot = th, thd
        self.steps += 1
        truncated = self.steps >= self.MAX_STEPS
        return self._obs(), -cost, False, truncated, {}


_REGISTRY = {"CartPole-v1": CartPole, "Pendulum-v1": Pendulum}


def make_env(name_or_fn: Any, seed: Optional[int] = None):
    if callable(name_or_fn):
        # pass the per-runner seed through when the factory accepts one —
        # otherwise every runner would sample identical episodes
        import inspect

        try:
            sig = inspect.signature(name_or_fn)
            if "seed" in sig.parameters:
                return name_or_fn(seed=seed)
        except (TypeError, ValueError):
            pass
        return name_or_fn()
    cls = _REGISTRY.get(name_or_fn)
    if cls is None:
        raise ValueError(f"unknown env {name_or_fn!r}; register a factory "
                         f"callable or use one of {sorted(_REGISTRY)}")
    return cls(seed=seed)
