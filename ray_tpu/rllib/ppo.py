"""PPO on jax: the new-API-stack shape (ref: rllib/algorithms/ppo/,
core/learner/learner.py:107, core/rl_module/, env/env_runner_group.py:71)
rebuilt TPU-first — the learner update is ONE jitted function (GAE +
clipped surrogate + value/entropy losses + adam), so the math compiles
onto the device while sampling stays on CPU actors.

    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=2)
              .training(lr=3e-4, train_batch_size=2000))
    algo = config.build()
    for _ in range(10):
        metrics = algo.train()
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointableAlgorithm
from .env import make_env

# ---------------------------------------------------------------------------
# Policy/value network: a functional MLP RLModule.
# ---------------------------------------------------------------------------


def init_policy(key, obs_dim: int, act_dim: int, hidden: Tuple[int, ...]):
    import jax
    import jax.numpy as jnp

    sizes = (obs_dim,) + hidden
    params = {"layers": [], "pi": None, "vf": None}
    keys = jax.random.split(key, len(hidden) + 2)
    for i in range(len(hidden)):
        params["layers"].append({
            "w": jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i]),
            "b": jnp.zeros(sizes[i + 1]),
        })
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (sizes[-1], act_dim)) * 0.01,
        "b": jnp.zeros(act_dim),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
        "b": jnp.zeros(1),
    }
    return params


def policy_forward(params, obs):
    import jax
    import jax.numpy as jnp

    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


# ---------------------------------------------------------------------------
# Env runner: one sampling actor (ref: single_agent_env_runner.py).
# ---------------------------------------------------------------------------


class EnvRunner:
    def __init__(self, env_spec, hidden: Tuple[int, ...], seed: int):
        self.env = make_env(env_spec, seed=seed)
        self.hidden = tuple(hidden)
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self._params = None
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed: List[float] = []

    def set_params(self, params) -> bool:
        self._params = params
        return True

    def _act(self, obs: np.ndarray) -> Tuple[int, float, float]:
        import jax.numpy as jnp

        logits, value = policy_forward(self._params,
                                       jnp.asarray(obs[None, :]))
        logits = np.asarray(logits)[0].astype(np.float64)
        logits -= logits.max()
        probs = np.exp(logits)
        probs /= probs.sum()
        action = int(self.rng.choice(len(probs), p=probs))
        return action, float(np.log(probs[action])), float(value[0])

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect a fixed-size rollout fragment (episodes continue
        across calls; the fragment carries bootstrap values)."""
        obs_buf = np.zeros((num_steps, len(self._obs)), np.float32)
        act_buf = np.zeros(num_steps, np.int32)
        rew_buf = np.zeros(num_steps, np.float32)
        done_buf = np.zeros(num_steps, np.float32)
        logp_buf = np.zeros(num_steps, np.float32)
        val_buf = np.zeros(num_steps, np.float32)
        for t in range(num_steps):
            action, logp, value = self._act(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = logp
            val_buf[t] = value
            obs, reward, terminated, truncated, _ = self.env.step(action)
            rew_buf[t] = reward
            self._episode_return += reward
            done = terminated or truncated
            done_buf[t] = float(done)
            if done:
                self._completed.append(self._episode_return)
                self._episode_return = 0.0
                obs, _ = self.env.reset()
            self._obs = obs
        _, bootstrap = self._act(self._obs)[1:]
        completed, self._completed = self._completed, []
        return {"obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "dones": done_buf, "logp": logp_buf, "values": val_buf,
                "bootstrap_value": np.float32(bootstrap),
                "episode_returns": np.asarray(completed, np.float32)}


# ---------------------------------------------------------------------------
# Learner: the jitted PPO update (ref: core/learner/learner.py — here the
# whole epoch loop is device-side).
# ---------------------------------------------------------------------------


def _gae(rewards, values, dones, bootstrap, gamma, lam):
    """Generalized advantage estimation over one fragment (host side —
    trivially cheap next to the update)."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last = 0.0
    next_value = bootstrap
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
        next_value = values[t]
    return adv, adv + values


_PPO_UPDATE_JIT = None


def ppo_update(params, opt_state, batch, key, lr, *, clip: float,
               vf_coef: float, ent_coef: float, n_minibatches: int,
               n_epochs: int):
    """All epochs and minibatches of one PPO iteration in a single
    compiled program (lax.scan over shuffled minibatch slices). Jitted
    lazily on first call — EnvRunner actor processes that only run
    policy_forward never pay jax-compile startup for the update."""
    global _PPO_UPDATE_JIT
    if _PPO_UPDATE_JIT is None:
        import jax

        _PPO_UPDATE_JIT = jax.jit(
            _ppo_update_impl,
            static_argnames=("clip", "vf_coef", "ent_coef",
                             "n_minibatches", "n_epochs"))
    return _PPO_UPDATE_JIT(params, opt_state, batch, key, lr, clip=clip,
                           vf_coef=vf_coef, ent_coef=ent_coef,
                           n_minibatches=n_minibatches, n_epochs=n_epochs)


def _ppo_update_impl(params, opt_state, batch, key, lr, *, clip: float,
                     vf_coef: float, ent_coef: float, n_minibatches: int,
                     n_epochs: int):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)
    N = batch["obs"].shape[0]
    mb = N // n_minibatches

    def loss_fn(p, idx):
        obs = batch["obs"][idx]
        logits, value = policy_forward(p, obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][idx][:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - batch["logp"][idx])
        adv = batch["advantages"][idx]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        pi_loss = -surr.mean()
        vf_loss = jnp.square(value - batch["returns"][idx]).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coef * vf_loss - ent_coef * entropy
        return total, (pi_loss, vf_loss, entropy)

    def epoch(carry, ekey):
        p, opt = carry
        perm = jax.random.permutation(ekey, N)

        def minibatch(carry, i):
            p, opt = carry
            idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p, idx)
            updates, opt = optimizer.update(grads, opt, p)
            p = optax.apply_updates(p, updates)
            return (p, opt), (loss, *aux)

        (p, opt), metrics = jax.lax.scan(
            minibatch, (p, opt), jnp.arange(n_minibatches))
        return (p, opt), metrics

    keys = jax.random.split(key, n_epochs)
    (params, opt_state), metrics = jax.lax.scan(
        epoch, (params, opt_state), keys)
    flat = jax.tree.map(lambda m: m.mean(), metrics)
    return params, opt_state, {
        "total_loss": flat[0], "policy_loss": flat[1],
        "vf_loss": flat[2], "entropy": flat[3]}


# ---------------------------------------------------------------------------
# Config + Algorithm (ref: algorithm_config.py builder / algorithm.py).
# ---------------------------------------------------------------------------


@dataclass
class PPOConfig:
    env: Any = "CartPole-v1"
    num_env_runners: int = 2
    rollout_fragment_length: int = 512
    train_batch_size: int = 1024          # derived check only
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 8
    num_minibatches: int = 8
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0

    # builder-style setters (ref: AlgorithmConfig fluent API)
    def environment(self, env) -> "PPOConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO(CheckpointableAlgorithm):
    """The Algorithm: env-runner actors sample in parallel, the jitted
    learner updates, new weights broadcast (ref: algorithm.py
    training_step:1749)."""

    def __init__(self, config: PPOConfig):
        import jax

        self.config = config
        probe = make_env(config.env, seed=0)
        self.obs_dim = probe.observation_dim
        self.act_dim = probe.action_dim
        key = jax.random.PRNGKey(config.seed)
        self.params = init_policy(key, self.obs_dim, self.act_dim,
                                  config.hidden)
        import optax

        self.opt_state = optax.adam(config.lr).init(self.params)
        self._key = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0

        import ray_tpu

        runner_cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.hidden,
                              config.seed + 100 + i)
            for i in range(config.num_env_runners)
        ]
        from .checkpoint import broadcast_suppressed

        if not broadcast_suppressed():  # from_checkpoint
            # restores real weights right after construction
            self._broadcast()

    def _broadcast(self) -> None:
        import ray_tpu

        host_params = __import__("jax").tree.map(np.asarray, self.params)
        ray_tpu.get([r.set_params.remote(host_params)
                     for r in self.runners], timeout=120)

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        import ray_tpu

        cfg = self.config
        frags = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self.runners], timeout=300)
        # GAE per fragment, then concatenate into the train batch
        obs, acts, logps, advs, rets, ep_returns = [], [], [], [], [], []
        for frag in frags:
            adv, ret = _gae(frag["rewards"], frag["values"], frag["dones"],
                            frag["bootstrap_value"], cfg.gamma, cfg.lambda_)
            obs.append(frag["obs"])
            acts.append(frag["actions"])
            logps.append(frag["logp"])
            advs.append(adv)
            rets.append(ret)
            ep_returns.extend(frag["episode_returns"].tolist())
        batch = {
            "obs": jnp.asarray(np.concatenate(obs)),
            "actions": jnp.asarray(np.concatenate(acts)),
            "logp": jnp.asarray(np.concatenate(logps)),
            "advantages": jnp.asarray(np.concatenate(advs)),
            "returns": jnp.asarray(np.concatenate(rets)),
        }
        self._key, subkey = jax.random.split(self._key)
        self.params, self.opt_state, losses = ppo_update(
            self.params, self.opt_state, batch, subkey, cfg.lr,
            clip=cfg.clip_param, vf_coef=cfg.vf_loss_coeff,
            ent_coef=cfg.entropy_coeff,
            n_minibatches=cfg.num_minibatches, n_epochs=cfg.num_epochs)
        self._broadcast()
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (float(np.mean(ep_returns))
                                    if ep_returns else float("nan")),
            "episodes_this_iter": len(ep_returns),
            "timesteps_this_iter": int(batch["obs"].shape[0]),
            **{k: float(v) for k, v in losses.items()},
        }

    def stop(self) -> None:
        import ray_tpu

        for runner in self.runners:
            try:
                ray_tpu.kill(runner)
            except Exception:
                pass
        self.runners = []
