"""DQN on jax (ref: rllib/algorithms/dqn/ — new-API-stack shape like
ppo.py here): epsilon-greedy env-runner actors feed a replay buffer; the
learner update (double-DQN TD loss + adam + periodic target sync) is one
jitted function, so the math compiles onto the device while sampling
stays on CPU actors.

    algo = (DQNConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2)
            .training(lr=1e-3, train_batch_size=64)).build()
    for _ in range(20):
        metrics = algo.train()
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointableAlgorithm
from .env import make_env
from .ppo import init_policy  # same MLP trunk; the pi head doubles as Q


def q_forward(params, obs):
    """Q-values per action: the MLP's 'pi' head read as Q(s, ·)."""
    import jax.numpy as jnp

    x = obs
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ params["pi"]["w"] + params["pi"]["b"]


class ReplayBuffer:
    """Uniform ring replay (ref: rllib/utils/replay_buffers/).

    ``act_shape``/``act_dtype`` cover both action spaces: DQN stores
    scalar int32 actions, SAC stores float32 vectors."""

    def __init__(self, capacity: int, obs_dim: int,
                 act_shape: tuple = (), act_dtype=np.int32):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, *act_shape), act_dtype)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.size = 0
        self._next = 0

    def add_batch(self, frag: Dict[str, np.ndarray]) -> None:
        n = len(frag["actions"])
        for i in range(n):
            j = self._next
            self.obs[j] = frag["obs"][i]
            self.next_obs[j] = frag["next_obs"][i]
            self.actions[j] = frag["actions"][i]
            self.rewards[j] = frag["rewards"][i]
            self.dones[j] = frag["dones"][i]
            self._next = (self._next + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, rng: np.random.Generator, batch: int):
        idx = rng.integers(0, self.size, batch)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx]}


class DQNEnvRunner:
    """Epsilon-greedy sampling actor (ref: single_agent_env_runner.py)."""

    def __init__(self, env_spec, hidden: Tuple[int, ...], seed: int):
        self.env = make_env(env_spec, seed=seed)
        self.rng = np.random.default_rng(seed)
        self._params = None
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed: List[float] = []

    def set_params(self, params) -> bool:
        self._params = params
        return True

    def sample(self, num_steps: int,
               epsilon: float) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        obs_dim = len(self._obs)
        out = {k: np.zeros((num_steps, obs_dim), np.float32)
               for k in ("obs", "next_obs")}
        out["actions"] = np.zeros(num_steps, np.int32)
        out["rewards"] = np.zeros(num_steps, np.float32)
        out["dones"] = np.zeros(num_steps, np.float32)
        for t in range(num_steps):
            if self.rng.random() < epsilon:
                action = int(self.rng.integers(self.env.action_dim))
            else:
                q = np.asarray(q_forward(self._params,
                                         jnp.asarray(self._obs[None, :])))
                action = int(q[0].argmax())
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            done = terminated or truncated
            out["obs"][t] = self._obs
            out["next_obs"][t] = nxt
            out["actions"][t] = action
            out["rewards"][t] = reward
            out["dones"][t] = float(terminated)  # truncation bootstraps
            self._episode_return += reward
            if done:
                self._completed.append(self._episode_return)
                self._episode_return = 0.0
                nxt, _ = self.env.reset()
            self._obs = nxt
        completed, self._completed = self._completed, []
        out["episode_returns"] = np.asarray(completed, np.float32)
        return out


_DQN_UPDATE_JIT = None


def dqn_update(params, target_params, opt_state, batch, lr, *,
               gamma: float, n_updates: int):
    """``n_updates`` double-DQN steps in one compiled program."""
    global _DQN_UPDATE_JIT
    if _DQN_UPDATE_JIT is None:
        import jax

        _DQN_UPDATE_JIT = jax.jit(
            _dqn_update_impl, static_argnames=("gamma", "n_updates"))
    return _DQN_UPDATE_JIT(params, target_params, opt_state, batch, lr,
                           gamma=gamma, n_updates=n_updates)


def _dqn_update_impl(params, target_params, opt_state, batch, lr, *,
                     gamma: float, n_updates: int):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)
    N = batch["obs"].shape[0]
    mb = N // n_updates

    def loss_fn(p, sl):
        q = q_forward(p, batch["obs"][sl])
        q_sel = jnp.take_along_axis(
            q, batch["actions"][sl][:, None], axis=1)[:, 0]
        # double DQN: online net picks the argmax, target net scores it
        q_next_online = q_forward(p, batch["next_obs"][sl])
        best = jnp.argmax(q_next_online, axis=1)
        q_next_target = q_forward(target_params, batch["next_obs"][sl])
        q_best = jnp.take_along_axis(q_next_target, best[:, None],
                                     axis=1)[:, 0]
        target = (batch["rewards"][sl]
                  + gamma * (1.0 - batch["dones"][sl])
                  * jax.lax.stop_gradient(q_best))
        td = q_sel - target
        return jnp.square(td).mean(), jnp.abs(td).mean()

    def step(carry, i):
        p, opt = carry
        sl = jax.lax.dynamic_slice_in_dim(jnp.arange(N), i * mb, mb)
        (loss, td_abs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, sl)
        updates, opt = optimizer.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        return (p, opt), (loss, td_abs)

    (params, opt_state), (losses, tds) = jax.lax.scan(
        step, (params, opt_state), jnp.arange(n_updates))
    return params, opt_state, {"td_loss": losses.mean(),
                               "td_abs": tds.mean()}


@dataclass
class DQNConfig:
    env: Any = "CartPole-v1"
    num_env_runners: int = 1
    rollout_fragment_length: int = 128
    train_batch_size: int = 64
    lr: float = 1e-3
    gamma: float = 0.99
    hidden: Tuple[int, ...] = (64, 64)
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    updates_per_iter: int = 8
    target_update_interval: int = 4      # iterations between target syncs
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_iters: int = 30
    seed: int = 0

    def environment(self, env) -> "DQNConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "DQNConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQN(CheckpointableAlgorithm):
    """Algorithm driver (ref: algorithms/dqn/dqn.py training_step):
    sample in parallel -> replay add -> minibatch updates -> periodic
    target sync -> broadcast."""

    def __init__(self, config: DQNConfig):
        import jax
        import optax

        self.config = config
        probe = make_env(config.env, seed=0)
        self.obs_dim = probe.observation_dim
        self.act_dim = probe.action_dim
        key = jax.random.PRNGKey(config.seed)
        self.params = init_policy(key, self.obs_dim, self.act_dim,
                                  config.hidden)
        self.target_params = jax.tree.map(lambda a: a, self.params)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.buffer = ReplayBuffer(config.buffer_capacity, self.obs_dim)
        self.np_rng = np.random.default_rng(config.seed)
        self.iteration = 0

        import ray_tpu

        runner_cls = ray_tpu.remote(DQNEnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.hidden,
                              config.seed + 200 + i)
            for i in range(config.num_env_runners)
        ]
        from .checkpoint import broadcast_suppressed

        if not broadcast_suppressed():  # from_checkpoint
            # restores real weights right after construction
            self._broadcast()

    def _extra_state(self):
        import jax

        # replay buffer intentionally excluded (refills from sampling);
        # the target net is learner state and must survive
        return {"target_params": jax.tree.map(np.asarray,
                                              self.target_params)}

    def _apply_extra_state(self, state):
        import jax
        import jax.numpy as jnp

        if "target_params" in state:
            self.target_params = jax.tree.map(jnp.asarray,
                                              state["target_params"])

    def _broadcast(self) -> None:
        import jax
        import ray_tpu

        host = jax.tree.map(np.asarray, self.params)
        ray_tpu.get([r.set_params.remote(host) for r in self.runners],
                    timeout=120)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        import ray_tpu

        cfg = self.config
        eps = self._epsilon()
        frags = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length, eps)
             for r in self.runners], timeout=300)
        ep_returns: List[float] = []
        for frag in frags:
            ep_returns.extend(frag.pop("episode_returns").tolist())
            self.buffer.add_batch(frag)

        losses = {"td_loss": float("nan"), "td_abs": float("nan")}
        if self.buffer.size >= max(cfg.learning_starts,
                                   cfg.train_batch_size):
            batch_np = self.buffer.sample(
                self.np_rng, cfg.train_batch_size * cfg.updates_per_iter)
            batch = {k: jnp.asarray(v) for k, v in batch_np.items()}
            self.params, self.opt_state, metrics = dqn_update(
                self.params, self.target_params, self.opt_state, batch,
                cfg.lr, gamma=cfg.gamma, n_updates=cfg.updates_per_iter)
            losses = {k: float(v) for k, v in metrics.items()}
            if self.iteration % cfg.target_update_interval == 0:
                self.target_params = jax.tree.map(lambda a: a, self.params)
            self._broadcast()

        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "epsilon": eps,
            "buffer_size": self.buffer.size,
            "episode_reward_mean": (float(np.mean(ep_returns))
                                    if ep_returns else float("nan")),
            "episodes_this_iter": len(ep_returns),
            **losses,
        }

    def stop(self) -> None:
        import ray_tpu

        for runner in self.runners:
            try:
                ray_tpu.kill(runner)
            except Exception:
                pass
        self.runners = []
