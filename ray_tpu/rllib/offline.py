"""Offline RL: rollout recording, a dataset reader, BC and MARWIL.

Reference analog: rllib/offline/ (dataset writers/readers feeding
offline algorithms) + rllib/algorithms/{bc,marwil}. The dataset rides
ray_tpu.data (npz shards -> Dataset), so offline training composes with
the same data plane everything else uses.

  * BC — behavior cloning: maximize log pi(a|s) over the dataset.
  * MARWIL — advantage-weighted BC (Wang et al. 2018): a value baseline
    is regressed on monte-carlo returns and the imitation term is
    weighted exp(beta * normalized advantage), so better-than-average
    behavior is imitated harder. beta=0 reduces exactly to BC.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointableAlgorithm
from .env import make_env
from .ppo import init_policy, policy_forward

__all__ = ["record_rollouts", "read_rollouts", "rollout_dataset",
           "BC", "BCConfig", "MARWIL", "MARWILConfig"]


# ---------------------------------------------------------------------------
# Dataset: write/read npz shards of (obs, action, reward, done) steps.
# ---------------------------------------------------------------------------


def record_rollouts(env_spec: Any, path: str, *, num_steps: int,
                    policy_params=None, hidden=(64, 64), seed: int = 0,
                    shard_steps: int = 4096) -> List[str]:
    """Roll a policy (random when params is None) and write npz shards.
    Returns the shard paths (ref: rllib/offline/output_writer)."""
    env = make_env(env_spec, seed=seed)
    rng = np.random.default_rng(seed)
    os.makedirs(path, exist_ok=True)
    obs, _ = env.reset(seed=seed)
    shards: List[str] = []
    buf: Dict[str, list] = {k: [] for k in
                            ("obs", "actions", "rewards", "dones")}

    def flush():
        if not buf["obs"]:
            return
        shard_path = os.path.join(path, f"shard_{len(shards):05d}.npz")
        np.savez(shard_path,
                 obs=np.asarray(buf["obs"], np.float32),
                 actions=np.asarray(buf["actions"], np.int32),
                 rewards=np.asarray(buf["rewards"], np.float32),
                 dones=np.asarray(buf["dones"], np.float32))
        shards.append(shard_path)
        for v in buf.values():
            v.clear()

    for _ in range(num_steps):
        if policy_params is None:
            action = int(rng.integers(env.action_dim))
        else:
            import jax.numpy as jnp

            logits, _ = policy_forward(policy_params,
                                       jnp.asarray(obs[None, :]))
            logits = np.asarray(logits)[0].astype(np.float64)
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            action = int(rng.choice(len(probs), p=probs))
        nxt, reward, terminated, truncated, _ = env.step(action)
        buf["obs"].append(obs)
        buf["actions"].append(action)
        buf["rewards"].append(reward)
        buf["dones"].append(float(terminated or truncated))
        obs = nxt
        if terminated or truncated:
            obs, _ = env.reset()
        if len(buf["obs"]) >= shard_steps:
            flush()
    flush()
    return shards


def read_rollouts(path: str) -> Dict[str, np.ndarray]:
    """All shards under `path`, concatenated (ref: offline input
    readers)."""
    shards = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".npz"))
    if not shards:
        raise FileNotFoundError(f"no .npz rollout shards under {path}")
    parts = [np.load(s) for s in shards]
    return {k: np.concatenate([p[k] for p in parts])
            for k in ("obs", "actions", "rewards", "dones")}


def rollout_dataset(path: str):
    """The shards as a ray_tpu.data Dataset of step rows — the offline
    pipeline entry for transforms/splits before training."""
    from .. import data as rdata

    rows = read_rollouts(path)
    n = len(rows["actions"])
    return rdata.from_items([
        {k: rows[k][i] for k in rows} for i in range(n)])


def _mc_returns(rewards: np.ndarray, dones: np.ndarray,
                gamma: float) -> np.ndarray:
    """Monte-carlo return-to-go per step, cut at episode bounds."""
    out = np.zeros_like(rewards)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        acc = rewards[t] + gamma * acc * (1.0 - dones[t])
        out[t] = acc
    return out


# ---------------------------------------------------------------------------
# BC / MARWIL learners (one jitted epoch; beta=0 == BC).
# ---------------------------------------------------------------------------

_MARWIL_JIT = None


def _marwil_update(params, opt_state, batch, lr, *, beta: float,
                   vf_coef: float):
    global _MARWIL_JIT
    if _MARWIL_JIT is None:
        import jax

        _MARWIL_JIT = jax.jit(_marwil_impl,
                              static_argnames=("beta", "vf_coef"))
    return _MARWIL_JIT(params, opt_state, batch, lr, beta=beta,
                       vf_coef=vf_coef)


def _marwil_impl(params, opt_state, batch, lr, *, beta: float,
                 vf_coef: float):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)

    def loss_fn(p):
        logits, values = policy_forward(p, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1)[:, 0]
        adv = batch["returns"] - jax.lax.stop_gradient(values)
        if beta > 0.0:
            norm = jnp.sqrt(jnp.mean(jnp.square(adv)) + 1e-8)
            weight = jnp.exp(jnp.clip(beta * adv / norm, -5.0, 5.0))
        else:
            weight = jnp.ones_like(adv)  # pure BC
        imitation = -(jax.lax.stop_gradient(weight) * logp).mean()
        vf_loss = jnp.square(values - batch["returns"]).mean()
        total = imitation + vf_coef * vf_loss
        return total, (imitation, vf_loss, logp.mean())

    (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, {"total_loss": loss, "imitation_loss": aux[0],
                               "vf_loss": aux[1], "mean_logp": aux[2]}


@dataclass
class MARWILConfig:
    env: Any = "CartPole-v1"          # for obs/act dims + eval
    input_path: str = ""              # rollout shard directory
    beta: float = 1.0                 # 0.0 == behavior cloning
    lr: float = 1e-3
    gamma: float = 0.99
    vf_loss_coeff: float = 1.0
    train_batch_size: int = 512
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def environment(self, env) -> "MARWILConfig":
        self.env = env
        return self

    def offline_data(self, input_path: str) -> "MARWILConfig":
        self.input_path = input_path
        return self

    def training(self, **kwargs) -> "MARWILConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self) -> "MARWIL":
        return MARWIL(self)


class MARWIL(CheckpointableAlgorithm):
    def __init__(self, config: MARWILConfig):
        import jax
        import optax

        self.config = config
        probe = make_env(config.env, seed=0)
        self.obs_dim = probe.observation_dim
        self.act_dim = probe.action_dim
        self.params = init_policy(jax.random.PRNGKey(config.seed),
                                  self.obs_dim, self.act_dim,
                                  config.hidden)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.iteration = 0
        rows = read_rollouts(config.input_path)
        self._data = {
            "obs": rows["obs"],
            "actions": rows["actions"],
            "returns": _mc_returns(rows["rewards"], rows["dones"],
                                   config.gamma),
        }
        self._rng = np.random.default_rng(config.seed)

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        n = len(self._data["actions"])
        idx = self._rng.integers(0, n, min(cfg.train_batch_size, n))
        batch = {
            "obs": jnp.asarray(self._data["obs"][idx]),
            "actions": jnp.asarray(self._data["actions"][idx]),
            "returns": jnp.asarray(self._data["returns"][idx]),
        }
        self.params, self.opt_state, losses = _marwil_update(
            self.params, self.opt_state, batch, cfg.lr,
            beta=cfg.beta, vf_coef=cfg.vf_loss_coeff)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "timesteps_this_iter": int(len(idx)),
                **{k: float(v) for k, v in losses.items()}}

    def evaluate(self, episodes: int = 5) -> Dict[str, float]:
        """Greedy policy rollouts in a live env — the offline algo's
        only ground truth."""
        import jax.numpy as jnp

        env = make_env(self.config.env, seed=self.config.seed + 999)
        returns = []
        for ep in range(episodes):
            obs, _ = env.reset(seed=self.config.seed + 1000 + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = policy_forward(self.params,
                                           jnp.asarray(obs[None, :]))
                action = int(np.asarray(logits)[0].argmax())
                obs, reward, terminated, truncated, _ = env.step(action)
                total += reward
                done = terminated or truncated
            returns.append(total)
        return {"episode_reward_mean": float(np.mean(returns)),
                "episodes": episodes}

    def stop(self) -> None:
        pass


@dataclass
class BCConfig(MARWILConfig):
    """Behavior cloning == MARWIL with beta pinned to 0
    (ref: rllib/algorithms/bc — same inheritance relationship)."""

    beta: float = 0.0

    def build(self) -> "BC":
        return BC(self)


class BC(MARWIL):
    pass
