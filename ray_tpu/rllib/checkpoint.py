"""Algorithm checkpointing (ref: rllib/utils/checkpoints.py
Checkpointable — save_to_path/restore_from_path on Algorithm)."""

from __future__ import annotations

import contextvars
import os
import pickle
from typing import Any, Dict

import cloudpickle
import numpy as np

# set while from_checkpoint constructs the algorithm: the constructor's
# initial broadcast of random weights would be immediately overwritten
# by the restored ones (two full broadcasts for one restore)
_RESTORING: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_rllib_restoring", default=False)


def broadcast_suppressed() -> bool:
    return _RESTORING.get()


class CheckpointableAlgorithm:
    """Mixin: save/restore learner state (params, opt state, iteration).
    Env-runner actors are rebuilt from config on restore and re-receive
    the weights via the algorithm's normal broadcast."""

    def _extra_state(self) -> Dict[str, Any]:
        return {}

    def _apply_extra_state(self, state: Dict[str, Any]) -> None:
        pass

    def save_to_path(self, path: str) -> str:
        import jax

        os.makedirs(path, exist_ok=True)
        state = {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "iteration": self.iteration,
            "config": self.config,
            **self._extra_state(),
        }
        # atomic: a crash mid-pickle must not destroy the previous
        # checkpoint at the same path
        final = os.path.join(path, "algorithm_state.pkl")
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            # cloudpickle: configs may carry callable env factories
            # (make_env supports them); plain pickle would crash here.
            # pickle.load reads cloudpickle output fine.
            cloudpickle.dump(state, f)
        os.replace(tmp, final)
        return path

    def _apply_state(self, state: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
            state["opt_state"])
        self.iteration = state["iteration"]
        self._apply_extra_state(state)
        self._broadcast()

    def restore_from_path(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self._apply_state(state)

    @classmethod
    def from_checkpoint(cls, path: str):
        """Rebuild the algorithm (and its runner actors) from a saved
        state's embedded config, then restore weights — the state file
        is read once, and the constructor's initial random-weight
        broadcast is suppressed (the restore broadcasts the real ones)."""
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        token = _RESTORING.set(True)
        try:
            algo = cls(state["config"])
        finally:
            _RESTORING.reset(token)
        algo._apply_state(state)
        return algo
