"""Algorithm checkpointing (ref: rllib/utils/checkpoints.py
Checkpointable — save_to_path/restore_from_path on Algorithm)."""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict

import numpy as np


class CheckpointableAlgorithm:
    """Mixin: save/restore learner state (params, opt state, iteration).
    Env-runner actors are rebuilt from config on restore and re-receive
    the weights via the algorithm's normal broadcast."""

    def _extra_state(self) -> Dict[str, Any]:
        return {}

    def _apply_extra_state(self, state: Dict[str, Any]) -> None:
        pass

    def save_to_path(self, path: str) -> str:
        import jax

        os.makedirs(path, exist_ok=True)
        state = {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "iteration": self.iteration,
            "config": self.config,
            **self._extra_state(),
        }
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return path

    def _apply_state(self, state: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
            state["opt_state"])
        self.iteration = state["iteration"]
        self._apply_extra_state(state)
        self._broadcast()

    def restore_from_path(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self._apply_state(state)

    @classmethod
    def from_checkpoint(cls, path: str):
        """Rebuild the algorithm (and its runner actors) from a saved
        state's embedded config, then restore weights — the state file
        is read and unpickled ONCE (it holds the full params)."""
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        algo = cls(state["config"])
        algo._apply_state(state)
        return algo
