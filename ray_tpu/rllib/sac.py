"""SAC on jax (ref: rllib/algorithms/sac/ — the new-API-stack shape the
other families here share): stochastic env-runner actors feed a replay
buffer; the learner update — twin soft Q critics, tanh-squashed
Gaussian actor, auto-tuned entropy temperature, polyak target tracking
— is ONE jitted program, so every gradient step of an iteration
compiles onto the device while sampling stays on CPU actors.

    algo = (SACConfig().environment("Pendulum-v1")
            .env_runners(num_env_runners=2)
            .training(lr=3e-4)).build()
    for _ in range(20):
        metrics = algo.train()
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointableAlgorithm
from .dqn import ReplayBuffer
from .env import make_env

_LOG_STD_MIN, _LOG_STD_MAX = -10.0, 2.0


# ---------------------------------------------------------------- networks


def _init_mlp(key, sizes):
    import jax
    import jax.numpy as jnp

    layers = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (n_in, n_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        layers.append({
            "w": jax.random.normal(k, (n_in, n_out), jnp.float32)
            * (2.0 / n_in) ** 0.5,
            "b": jnp.zeros((n_out,), jnp.float32),
        })
    return layers


def _mlp(layers, x, *, final_linear: bool = True):
    import jax.numpy as jnp

    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1 or not final_linear:
            x = jnp.tanh(x)
    return x


def init_sac_params(key, obs_dim: int, act_dim: int,
                    hidden: Tuple[int, ...]):
    import jax

    ka, k1, k2 = jax.random.split(key, 3)
    return {
        # actor emits [mu | log_std]
        "actor": _init_mlp(ka, (obs_dim, *hidden, 2 * act_dim)),
        "q1": _init_mlp(k1, (obs_dim + act_dim, *hidden, 1)),
        "q2": _init_mlp(k2, (obs_dim + act_dim, *hidden, 1)),
        # log alpha as a learnable scalar (entropy temperature)
        "log_alpha": 0.0,
    }


def actor_dist(params, obs):
    import jax.numpy as jnp

    out = _mlp(params["actor"], obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
    return mu, log_std


def sample_action(params, obs, key):
    """Reparameterized tanh-squashed Gaussian sample + its log-prob."""
    import jax
    import jax.numpy as jnp

    mu, log_std = actor_dist(params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    act = jnp.tanh(pre)
    # log N(pre; mu, std) minus the tanh change-of-variables term
    logp = (-0.5 * (((pre - mu) / std) ** 2
                    + 2 * log_std + math.log(2 * math.pi))).sum(-1)
    logp = logp - (2 * (math.log(2.0) - pre
                        - jax.nn.softplus(-2 * pre))).sum(-1)
    return act, logp


def _q(params_q, obs, act):
    import jax.numpy as jnp

    return _mlp(params_q, jnp.concatenate([obs, act], axis=-1))[..., 0]


# ---------------------------------------------------------------- learner

_SAC_UPDATE_JIT = None


def sac_update(params, target, opt_state, batch, key, *, lr: float,
               gamma: float, tau: float, target_entropy: float,
               n_updates: int):
    global _SAC_UPDATE_JIT
    if _SAC_UPDATE_JIT is None:
        import jax

        _SAC_UPDATE_JIT = jax.jit(
            _sac_update_impl,
            static_argnames=("lr", "gamma", "tau", "target_entropy",
                             "n_updates"))
    return _SAC_UPDATE_JIT(params, target, opt_state, batch, key, lr=lr,
                           gamma=gamma, tau=tau,
                           target_entropy=target_entropy,
                           n_updates=n_updates)


def _sac_update_impl(params, target, opt_state, batch, key, *, lr, gamma,
                     tau, target_entropy, n_updates):
    import jax
    import jax.numpy as jnp
    import optax

    optimizer = optax.adam(lr)
    N = batch["obs"].shape[0]
    mb = N // n_updates

    def loss_fn(p, tgt, sl, k):
        obs = batch["obs"][sl]
        nxt = batch["next_obs"][sl]
        act = batch["actions"][sl]
        alpha = jnp.exp(p["log_alpha"])
        k1, k2 = jax.random.split(k)

        # --- critic target: soft Bellman backup through both targets
        nact, nlogp = sample_action(p, nxt, k1)
        tq = jnp.minimum(_q(tgt["q1"], nxt, nact),
                         _q(tgt["q2"], nxt, nact))
        backup = batch["rewards"][sl] + gamma * (
            1.0 - batch["dones"][sl]) * jax.lax.stop_gradient(
                tq - alpha * nlogp)
        q1 = _q(p["q1"], obs, act)
        q2 = _q(p["q2"], obs, act)
        critic_loss = (jnp.square(q1 - backup)
                       + jnp.square(q2 - backup)).mean()

        # --- actor: maximize min-Q + entropy (critics held fixed)
        pact, plogp = sample_action(p, obs, k2)
        qpi = jnp.minimum(
            _q(jax.lax.stop_gradient(p["q1"]), obs, pact),
            _q(jax.lax.stop_gradient(p["q2"]), obs, pact))
        actor_loss = (jax.lax.stop_gradient(alpha) * plogp - qpi).mean()

        # --- temperature: drive entropy toward target_entropy
        alpha_loss = (-jnp.exp(p["log_alpha"])
                      * jax.lax.stop_gradient(plogp
                                              + target_entropy)).mean()
        total = critic_loss + actor_loss + alpha_loss
        return total, (critic_loss, actor_loss, alpha,
                       -plogp.mean())

    def step(carry, i):
        p, tgt, opt, k = carry
        k, sub = jax.random.split(k)
        sl = jax.lax.dynamic_slice_in_dim(jnp.arange(N), i * mb, mb)
        (_, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, tgt, sl, sub)
        updates, opt = optimizer.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        # polyak target tracking of the critics only
        tgt = {
            "q1": jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                               tgt["q1"], p["q1"]),
            "q2": jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                               tgt["q2"], p["q2"]),
        }
        return (p, tgt, opt, k), aux

    (params, target, opt_state, _), aux = jax.lax.scan(
        step, (params, target, opt_state, key), jnp.arange(n_updates))
    critic, actor, alpha, entropy = aux
    return params, target, opt_state, {
        "critic_loss": critic.mean(), "actor_loss": actor.mean(),
        "alpha": alpha[-1], "entropy": entropy.mean()}


# ---------------------------------------------------------------- sampling


class SACEnvRunner:
    """Stochastic-policy sampling actor over a continuous env."""

    def __init__(self, env_spec, hidden: Tuple[int, ...], seed: int):
        self.env = make_env(env_spec, seed=seed)
        self.max_torque = getattr(self.env, "MAX_TORQUE", 1.0)
        self.seed = seed
        self._params = None
        self._key = None
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed: List[float] = []

    def set_params(self, params) -> bool:
        import jax

        self._params = params
        if self._key is None:
            self._key = jax.random.PRNGKey(self.seed)
        return True

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        obs_dim = self.env.observation_dim
        act_dim = self.env.action_dim
        out = {"obs": np.zeros((num_steps, obs_dim), np.float32),
               "next_obs": np.zeros((num_steps, obs_dim), np.float32),
               "actions": np.zeros((num_steps, act_dim), np.float32),
               "rewards": np.zeros(num_steps, np.float32),
               "dones": np.zeros(num_steps, np.float32)}
        for t in range(num_steps):
            self._key, sub = jax.random.split(self._key)
            act, _ = sample_action(self._params,
                                   jnp.asarray(self._obs[None, :]), sub)
            act = np.asarray(act)[0]
            nxt, reward, terminated, truncated, _ = self.env.step(
                act * self.max_torque)
            out["obs"][t] = self._obs
            out["next_obs"][t] = nxt
            out["actions"][t] = act
            out["rewards"][t] = reward
            out["dones"][t] = float(terminated)
            self._episode_return += reward
            if terminated or truncated:
                self._completed.append(self._episode_return)
                self._episode_return = 0.0
                nxt, _ = self.env.reset()
            self._obs = nxt
        completed, self._completed = self._completed, []
        out["episode_returns"] = np.asarray(completed, np.float32)
        return out


# ---------------------------------------------------------------- algorithm


@dataclass
class SACConfig:
    env: Any = "Pendulum-v1"
    num_env_runners: int = 1
    rollout_fragment_length: int = 200
    train_batch_size: int = 256
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.01
    hidden: Tuple[int, ...] = (64, 64)
    buffer_capacity: int = 50_000
    learning_starts: int = 400
    updates_per_iter: int = 16
    target_entropy: Optional[float] = None   # default: -act_dim
    seed: int = 0

    def environment(self, env) -> "SACConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "SACConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "SACConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SAC(CheckpointableAlgorithm):
    """Algorithm driver (ref: algorithms/sac/sac.py training_step):
    sample -> replay add -> n jitted soft-actor-critic updates ->
    broadcast."""

    def __init__(self, config: SACConfig):
        import jax
        import optax

        self.config = config
        probe = make_env(config.env, seed=0)
        if not getattr(probe, "continuous", False):
            raise ValueError(
                "SAC here targets continuous-action envs (e.g. "
                "Pendulum-v1); use DQN/PPO/IMPALA for discrete ones")
        self.obs_dim = probe.observation_dim
        self.act_dim = probe.action_dim
        self.target_entropy = (config.target_entropy
                               if config.target_entropy is not None
                               else -float(self.act_dim))
        key = jax.random.PRNGKey(config.seed)
        self.params = init_sac_params(key, self.obs_dim, self.act_dim,
                                      config.hidden)
        self.target = {"q1": jax.tree.map(lambda a: a, self.params["q1"]),
                       "q2": jax.tree.map(lambda a: a, self.params["q2"])}
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.buffer = ReplayBuffer(
            config.buffer_capacity, self.obs_dim,
            act_shape=(self.act_dim,), act_dtype=np.float32)
        self.np_rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0

        import ray_tpu

        runner_cls = ray_tpu.remote(SACEnvRunner)
        self.runners = [
            runner_cls.remote(config.env, config.hidden,
                              config.seed + 300 + i)
            for i in range(config.num_env_runners)
        ]
        from .checkpoint import broadcast_suppressed

        if not broadcast_suppressed():
            self._broadcast()

    def _extra_state(self):
        import jax

        return {"target": jax.tree.map(np.asarray, self.target)}

    def _apply_extra_state(self, state):
        import jax
        import jax.numpy as jnp

        if "target" in state:
            self.target = jax.tree.map(jnp.asarray, state["target"])

    def _broadcast(self) -> None:
        import jax
        import ray_tpu

        host = jax.tree.map(np.asarray, self.params)
        ray_tpu.get([r.set_params.remote(host) for r in self.runners],
                    timeout=120)

    def train(self) -> Dict[str, Any]:
        import jax
        import ray_tpu

        cfg = self.config
        frags = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self.runners], timeout=300)
        returns: List[float] = []
        for frag in frags:
            returns.extend(frag.pop("episode_returns").tolist())
            self.buffer.add_batch(frag)

        metrics: Dict[str, Any] = {}
        if self.buffer.size >= cfg.learning_starts:
            batch = self.buffer.sample(
                self.np_rng, cfg.train_batch_size * cfg.updates_per_iter)
            self._key, sub = jax.random.split(self._key)
            self.params, self.target, self.opt_state, metrics = sac_update(
                self.params, self.target, self.opt_state, batch, sub,
                lr=cfg.lr, gamma=cfg.gamma, tau=cfg.tau,
                target_entropy=self.target_entropy,
                n_updates=cfg.updates_per_iter)
            metrics = {k: float(v) for k, v in metrics.items()}
            self._broadcast()
        self.iteration += 1
        metrics.update({
            "iteration": self.iteration,
            "buffer_size": self.buffer.size,
            "episode_return_mean": (float(np.mean(returns))
                                    if returns else float("nan")),
            "episodes_this_iter": len(returns),
        })
        return metrics
