"""APPO: asynchronous PPO (ref: rllib/algorithms/appo/) — the IMPALA
actor-learner architecture (async fragments, v-trace off-policy
correction) with PPO's clipped surrogate bounding each policy step.
Exactly IMPALA's machinery with clip_param > 0; see impala.py for the
jitted update."""

from __future__ import annotations

from dataclasses import dataclass

from .impala import IMPALA, IMPALAConfig

__all__ = ["APPO", "APPOConfig"]


@dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.3
    lr: float = 5e-4

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    """Async PPO driver — IMPALA's train loop, clipped update."""
