"""ray_tpu.llm: TPU-native LLM inference — paged KV cache, continuous
batching, serving (ref: python/ray/llm/ — which delegates to vLLM; here
the engine is native jax/XLA, SURVEY §2.4)."""

from .cache import KVCache, PageAllocator, SequenceTable, init_kv_cache
from .engine import EngineConfig, LLMEngine, StepOutput
from .sampling import SamplingParams
from .serve import LLMServer, build_llm_deployment

__all__ = [
    "LLMEngine", "EngineConfig", "StepOutput", "SamplingParams",
    "KVCache", "PageAllocator", "SequenceTable", "init_kv_cache",
    "LLMServer", "build_llm_deployment",
]
