"""Multi-LoRA serving: per-request low-rank adapters in ONE batched
engine (ref: the reference inherits LoRA serving from vLLM —
--enable-lora + per-request lora_request; its own multiplex layer is
adapter-agnostic. The batched-adapter design here is the S-LoRA /
punica shape, TPU-first).

Adapters live in a STACKED device pool — one tensor per projection:
``a_q [P, L, d, r]``, ``b_q [P, L, r, h*hd]`` (same for wv) — so a
decode batch where every slot wears a different adapter is one gather
(pool[ids]) plus two skinny einsums per projection, all inside the same
compiled program; slot 0 of the pool is the ZERO adapter (requests
without a model_id ride it and get exactly the base model). Pool size
is static (max_loras), so adapter add/swap never retraces."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LoRAPool", "init_lora_adapter", "lora_delta"]


def init_lora_adapter(key, cfg, rank: int, *, scale: float = 1.0,
                      dtype=jnp.bfloat16) -> Dict[str, Any]:
    """One adapter's weights (standard init: A ~ N(0, 1/r), B = 0 — a
    fresh adapter is an exact no-op until trained)."""
    L, d, hd = cfg.n_layers, cfg.dim, cfg.head_dim
    h, hkv = cfg.n_heads, cfg.n_kv_heads
    ka, kv = jax.random.split(key)
    return {
        "a_q": (jax.random.normal(ka, (L, d, rank), jnp.float32)
                * (rank ** -0.5)).astype(dtype),
        "b_q": jnp.zeros((L, rank, h * hd), dtype),
        "a_v": (jax.random.normal(kv, (L, d, rank), jnp.float32)
                * (rank ** -0.5)).astype(dtype),
        "b_v": jnp.zeros((L, rank, hkv * hd), dtype),
        "scale": jnp.float32(scale),
    }


def lora_delta(h, a_sel, b_sel, scale_sel, out_heads: int, head_dim: int):
    """Per-slot low-rank delta: h [B, S, d]; a_sel [B, d, r];
    b_sel [B, r, out]; scale_sel [B] -> [B, S, heads, head_dim]."""
    lo = jnp.einsum("bsd,bdr->bsr", h, a_sel)
    delta = jnp.einsum("bsr,bro->bso", lo, b_sel)
    delta = delta * scale_sel[:, None, None].astype(delta.dtype)
    B, S = h.shape[:2]
    return delta.reshape(B, S, out_heads, head_dim)


class LoRAPool:
    """Host-side registry + device-side stacked pool.

    Slot 0 is permanently the zero adapter. ``add`` uploads an adapter
    into a free slot; ``remove`` frees it (the pool tensor keeps its
    static shape — the slot is just zeroed lazily on reuse)."""

    def __init__(self, cfg, rank: int, max_loras: int,
                 dtype=jnp.bfloat16):
        if max_loras < 1:
            raise ValueError("max_loras must be >= 1")
        L, d, hd = cfg.n_layers, cfg.dim, cfg.head_dim
        h, hkv = cfg.n_heads, cfg.n_kv_heads
        P = max_loras + 1              # + the zero slot
        self.rank, self.max_loras = rank, max_loras
        self.cfg = cfg
        self.pool = {
            "a_q": jnp.zeros((P, L, d, rank), dtype),
            "b_q": jnp.zeros((P, L, rank, h * hd), dtype),
            "a_v": jnp.zeros((P, L, d, rank), dtype),
            "b_v": jnp.zeros((P, L, rank, hkv * hd), dtype),
            "scale": jnp.zeros((P,), jnp.float32),
        }
        self._slots: Dict[str, int] = {}
        self._free = list(range(P - 1, 0, -1))
        self._select_cache: Dict[tuple, Dict[str, Any]] = {}

    def __contains__(self, name: str) -> bool:
        return name in self._slots

    def __len__(self) -> int:
        return len(self._slots)

    def slot_of(self, name: Optional[str]) -> int:
        if name is None:
            return 0
        if name not in self._slots:
            raise KeyError(f"unknown LoRA adapter {name!r}; add_lora it "
                           f"first (loaded: {sorted(self._slots)})")
        return self._slots[name]

    def add(self, name: str, adapter: Dict[str, Any]) -> int:
        if name in self._slots:
            raise ValueError(f"adapter {name!r} already loaded")
        if not self._free:
            raise RuntimeError(
                f"LoRA pool full ({self.max_loras}); remove one first")
        slot = self._free.pop()
        for field in ("a_q", "b_q", "a_v", "b_v"):
            leaf = jnp.asarray(adapter[field],
                               self.pool[field].dtype)
            if leaf.shape != self.pool[field].shape[1:]:
                raise ValueError(
                    f"adapter {field} shape {leaf.shape} != pool "
                    f"{self.pool[field].shape[1:]}")
            self.pool[field] = self.pool[field].at[slot].set(leaf)
        self.pool["scale"] = self.pool["scale"].at[slot].set(
            jnp.float32(adapter.get("scale", 1.0)))
        self._select_cache.clear()
        self._slots[name] = slot
        return slot

    def remove(self, name: str) -> None:
        slot = self._slots.pop(name)
        # zero the scale: the slot's stale weights multiply to nothing,
        # so reuse can lazily overwrite without an eager wipe
        self.pool["scale"] = self.pool["scale"].at[slot].set(0.0)
        self._select_cache.clear()
        self._free.append(slot)

    def select(self, ids) -> Dict[str, Any]:
        """Per-slot adapter tensors for a batch: ids [B] ->
        {a_q [B, L, d, r], ...} (one gather per projection). Cached by
        the id tuple — steady-state decode re-selects the SAME batch
        assignment every burst and must not pay the gather again; any
        pool mutation (add/remove) invalidates."""
        key = tuple(int(i) for i in ids)
        cached = self._select_cache.get(key)
        if cached is not None:
            return cached
        idx = jnp.asarray(key, jnp.int32)
        out = {
            "a_q": self.pool["a_q"][idx],
            "b_q": self.pool["b_q"][idx],
            "a_v": self.pool["a_v"][idx],
            "b_v": self.pool["b_v"][idx],
            "scale": self.pool["scale"][idx],
        }
        if len(self._select_cache) > 64:
            self._select_cache.clear()
        self._select_cache[key] = out
        return out
