"""Model runner: jitted prefill + single-token decode over a paged KV
cache, for the Llama family.

TPU-first shape discipline (everything static under jit):
  * prefill pads the prompt to a power-of-2 bucket — one compiled
    executable per bucket, reused across requests;
  * decode runs the WHOLE slot batch [max_seqs] every step, inactive
    slots masked (their writes land on dump page 0) — one executable for
    the life of the engine;
  * cache buffers are donated, so XLA updates pages in place (no
    O(cache) copy per step).

The decode attention gathers pages with jnp.take (XLA fuses the gather
into the attention when it can); a Pallas in-place kernel is the upgrade
path once shapes are pinned. Reference analog: the vLLM paged-attention
CUDA kernels behind ray.llm's vllm_engine (SURVEY §2.4) — rebuilt here
natively since the reference delegates all device work to vLLM.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..ops import apply_rotary, attention, rms_norm, rope_frequencies
from ..ops.quant import embed_lookup, is_quantized, weight_einsum
from .cache import KVCache


def _mlp(h, lp, cfg: LlamaConfig):
    """Serving MLP: dense SwiGLU, or EXACT top-k MoE for expert configs.
    Inference must route drop-free (capacity-factor dispatch drops
    tokens batch-dependently, silently changing generations), so MoE
    uses the dense all-expert mixture — E-fold MLP FLOPs, the right
    trade at small E / decode batch sizes; see ops.moe.moe_mlp_dense
    for the large-E upgrade path."""
    if cfg.n_experts:
        from ..ops.moe import moe_mlp_dense

        return moe_mlp_dense(h, lp["router"], lp["w_gate"], lp["w_up"],
                             lp["w_down"], top_k=cfg.top_k)
    g = weight_einsum("bsd,dm->bsm", h, lp["w_gate"])
    u = weight_einsum("bsd,dm->bsm", h, lp["w_up"])
    return weight_einsum("bsm,md->bsd", jax.nn.silu(g) * u, lp["w_down"])


def _lm_logits(x_last, params, cfg: LlamaConfig):
    """Final-norm'd hidden -> f32 logits, raw or int8 lm_head. bf16
    operands on the MXU with f32 accumulation either way."""
    lm = params["lm_head"]
    if not is_quantized(lm):
        lm = lm.astype(cfg.dtype)
    return weight_einsum("bd,dv->bv", x_last.astype(cfg.dtype), lm,
                         preferred_element_type=jnp.float32)


def _write_pages(cache_layer, new, block_tables, positions, page_size):
    """Scatter per-token K or V rows into their pages.

    cache_layer: [P, page, kvh, hd]; new: [B, S, kvh, hd];
    block_tables: [B, max_pages]; positions: [B, S] absolute positions
    (negative = padding -> routed to dump page 0).
    """
    B, S = new.shape[:2]
    page_idx = jnp.take_along_axis(
        block_tables, jnp.maximum(positions, 0) // page_size, axis=1)
    valid = positions >= 0
    page_idx = jnp.where(valid, page_idx, 0)           # dump page
    offset = jnp.where(valid, positions % page_size, 0)
    flat_pages = page_idx.reshape(-1)                  # [B*S]
    flat_off = offset.reshape(-1)
    flat_new = new.reshape(B * S, *new.shape[2:])
    return cache_layer.at[flat_pages, flat_off].set(
        flat_new.astype(cache_layer.dtype), mode="drop")


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache_k",
                                                             "cache_v"))
def prefill(params, cache_k, cache_v, tokens, prompt_lens, block_tables,
            cos, sin, lora=None, *, cfg: LlamaConfig):
    """Process full prompts, fill their pages, return last-token logits.

    tokens: [B, S] right-padded; prompt_lens: [B]; block_tables: [B, Pmax].
    ``lora``: per-slot batched adapters from LoRAPool.select(ids) —
    low-rank deltas on wq/wv (llm/lora.py), empty/None = base model.
    Returns (logits [B, vocab], cache_k, cache_v).
    """
    from .lora import lora_delta

    B, S = tokens.shape
    x = embed_lookup(params["embed"], tokens, cfg.dtype)
    pos_grid = jnp.arange(S)[None, :].repeat(B, 0)
    write_pos = jnp.where(pos_grid < prompt_lens[:, None], pos_grid, -1)
    # adapters ride the layer scan as xs: [B, L, ...] -> [L, B, ...]
    lora_xs = {} if not lora else {
        k2: jnp.swapaxes(v2, 0, 1) for k2, v2 in lora.items()
        if k2 != "scale"}

    def layer(x, inputs):
        lp, ck, cv, lr = inputs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = weight_einsum("bsd,dhk->bshk", h, lp["wq"])
        k = weight_einsum("bsd,dhk->bshk", h, lp["wk"])
        v = weight_einsum("bsd,dhk->bshk", h, lp["wv"])
        if lr:
            q = q + lora_delta(h, lr["a_q"], lr["b_q"], lora["scale"],
                               cfg.n_heads, cfg.head_dim)
            v = v + lora_delta(h, lr["a_v"], lr["b_v"], lora["scale"],
                               cfg.n_kv_heads, cfg.head_dim)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        ck = _write_pages(ck, k, block_tables, write_pos, ck.shape[1])
        cv = _write_pages(cv, v, block_tables, write_pos, cv.shape[1])
        # right padding is safe under the causal mask: a real position
        # only attends to earlier (real) positions
        o = attention(q, k, v, causal=True)
        x = x + weight_einsum("bshk,hkd->bsd", o, lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        return x, (ck, cv)

    x, (cache_k, cache_v) = jax.lax.scan(
        layer, x, (params["layers"], cache_k, cache_v, lora_xs))
    x_last = jnp.take_along_axis(
        x, jnp.maximum(prompt_lens - 1, 0)[:, None, None], axis=1)[:, 0]
    x_last = rms_norm(x_last, params["final_norm"], cfg.norm_eps)
    logits = _lm_logits(x_last, params, cfg)
    return logits, cache_k, cache_v


def prefill_bucket(seq_len: int, max_seq: int, floor: int = 16) -> int:
    """Power-of-2 padding bucket — one compiled prefill per bucket."""
    b = floor
    while b < seq_len:
        b *= 2
    return min(b, max_seq)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache_k",
                                                             "cache_v"))
def prefill_chunk(params, cache_k, cache_v, tokens, start_pos, chunk_len,
                  block_tables, cos, sin, *, cfg: LlamaConfig):
    """One CHUNK of a long prompt (vLLM's chunked prefill, rebuilt for
    static shapes): tokens [1, C] are positions
    [start_pos, start_pos+chunk_len), attending causally within the
    chunk AND over the pages written by earlier chunks. One compiled
    executable per (C, table-span) pair serves prompts of every length —
    and decode bursts for other requests interleave between chunks, so a
    long prompt no longer stalls running streams for its whole prefill.

    Returns (logits [1, vocab] of the chunk's LAST VALID token,
    cache_k, cache_v).
    """
    B, C = tokens.shape
    page_size = cache_k.shape[2]
    Spast = block_tables.shape[1] * page_size
    x = embed_lookup(params["embed"], tokens, cfg.dtype)
    pos_grid = start_pos + jnp.arange(C)[None, :]          # [1, C]
    valid = jnp.arange(C)[None, :] < chunk_len
    write_pos = jnp.where(valid, pos_grid, -1)
    # past pages hold positions < start_pos (written by earlier chunks)
    past_mask = jnp.arange(Spast)[None, :] < start_pos     # [1, Spast]
    chunk_mask = (jnp.arange(C)[None, :, None]
                  >= jnp.arange(C)[None, None, :]) & valid[:, None, :]

    def layer(x, inputs):
        lp, ck, cv = inputs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = weight_einsum("bsd,dhk->bshk", h, lp["wq"])
        k = weight_einsum("bsd,dhk->bshk", h, lp["wk"])
        v = weight_einsum("bsd,dhk->bshk", h, lp["wv"])
        q = apply_rotary(q, cos, sin, positions=pos_grid)
        k = apply_rotary(k, cos, sin, positions=pos_grid)
        ck = _write_pages(ck, k, block_tables, write_pos, page_size)
        cv = _write_pages(cv, v, block_tables, write_pos, page_size)
        pk = jnp.take(ck, block_tables, axis=0).reshape(
            B, Spast, *k.shape[2:])
        pv = jnp.take(cv, block_tables, axis=0).reshape(
            B, Spast, *v.shape[2:])
        kvh, hd = cfg.n_kv_heads, cfg.head_dim
        rep = cfg.n_heads // kvh
        qg = q.reshape(B, C, kvh, rep, hd)
        scale = hd ** -0.5
        s_past = jnp.einsum("bcgrd,bsgd->bcgrs", qg, pk,
                            preferred_element_type=jnp.float32)
        s_self = jnp.einsum("bcgrd,btgd->bcgrt", qg, k,
                            preferred_element_type=jnp.float32)
        s_past = jnp.where(past_mask[:, None, None, None, :],
                           s_past * scale, -jnp.inf)
        s_self = jnp.where(chunk_mask[:, :, None, None, :],
                           s_self * scale, -jnp.inf)
        p = jax.nn.softmax(
            jnp.concatenate([s_past, s_self], axis=-1), axis=-1
        ).astype(pk.dtype)
        o = (jnp.einsum("bcgrs,bsgd->bcgrd", p[..., :Spast], pv,
                        preferred_element_type=jnp.float32)
             + jnp.einsum("bcgrt,btgd->bcgrd", p[..., Spast:], v,
                          preferred_element_type=jnp.float32))
        o = o.reshape(B, C, cfg.n_heads, hd).astype(x.dtype)
        x = x + weight_einsum("bshk,hkd->bsd", o, lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        return x, (ck, cv)

    x, (cache_k, cache_v) = jax.lax.scan(
        layer, x, (params["layers"], cache_k, cache_v))
    idx = jnp.broadcast_to(jnp.maximum(chunk_len - 1, 0).reshape(1, 1, 1),
                           (B, 1, 1))
    x_last = jnp.take_along_axis(x, idx, axis=1)[:, 0]
    x_last = rms_norm(x_last, params["final_norm"], cfg.norm_eps)
    logits = _lm_logits(x_last, params, cfg)
    return logits, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg", "greedy"),
         donate_argnames=("cache_k", "cache_v"))
def verify_step(params, cache_k, cache_v, tokens, positions, block_tables,
                cos, sin, seed, temperature, top_k, top_p, *,
                cfg: LlamaConfig, greedy: bool = False):
    """Batched multi-token verification forward (speculative decoding,
    Leviathan et al. ICML'23 — PAPERS.md): score a whole k-token draft
    window in ONE dispatch, like a short prefill over the paged cache.

    tokens: [B, S] window tokens (row = [last_emitted, d_1 .. d_k]);
    positions: [B, S] absolute per-token positions, -1 = padding (rows
    with shorter windows, undrafted slots) — padded writes land on dump
    page 0. Every valid window token's KV is WRITTEN first, then
    attention gathers the pages, masked by key_pos <= query_pos: the
    window's own keys are visible through the pages (write-then-gather,
    same discipline as prefill_chunk), stale rows from a previous
    rejected window sit at positions > query_pos and never score.

    Returns (argmax tokens [B, S] — index j predicts the token AFTER
    window position j, sampled position-0 token [B] for rows that
    aren't greedy, cache_k, cache_v).
    """
    from .sampling import sample_from_logits

    B, S = tokens.shape
    page_size = cache_k.shape[2]
    Sall = block_tables.shape[1] * page_size
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // kvh
    x = embed_lookup(params["embed"], tokens, cfg.dtype)
    qpos = jnp.maximum(positions, 0)                       # [B, S]
    # unused table slots are 0 (dump page) but sit past the row's
    # provisioned span, so their key positions exceed every query's
    kmask = (jnp.arange(Sall)[None, None, :]
             <= qpos[:, :, None])                          # [B, S, Sall]

    def layer(x, inputs):
        lp, ck, cv = inputs
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = weight_einsum("bsd,dhk->bshk", h, lp["wq"])
        k = weight_einsum("bsd,dhk->bshk", h, lp["wk"])
        v = weight_einsum("bsd,dhk->bshk", h, lp["wv"])
        q = apply_rotary(q, cos, sin, positions=qpos)
        k = apply_rotary(k, cos, sin, positions=qpos)
        ck = _write_pages(ck, k, block_tables, positions, page_size)
        cv = _write_pages(cv, v, block_tables, positions, page_size)
        pk = jnp.take(ck, block_tables, axis=0).reshape(B, Sall, kvh, hd)
        pv = jnp.take(cv, block_tables, axis=0).reshape(B, Sall, kvh, hd)
        qg = q.reshape(B, S, kvh, rep, hd)
        s = jnp.einsum("bsgrd,btgd->bsgrt", qg, pk,
                       preferred_element_type=jnp.float32)
        s = jnp.where(kmask[:, :, None, None, :], s * (hd ** -0.5),
                      -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(pk.dtype)
        o = jnp.einsum("bsgrt,btgd->bsgrd", p, pv,
                       preferred_element_type=jnp.float32)
        o = o.reshape(B, S, cfg.n_heads, hd).astype(x.dtype)
        x = x + weight_einsum("bshk,hkd->bsd", o, lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(h, lp, cfg)
        return x, (ck, cv)

    x, (cache_k, cache_v) = jax.lax.scan(
        layer, x, (params["layers"], cache_k, cache_v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    lm = params["lm_head"]
    if not is_quantized(lm):
        lm = lm.astype(cfg.dtype)
    logits = weight_einsum("bsd,dv->bsv", x.astype(cfg.dtype), lm,
                           preferred_element_type=jnp.float32)
    tgt = jnp.argmax(logits, axis=-1)                      # [B, S]
    if greedy:
        samp0 = tgt[:, 0]
    else:
        samp0 = sample_from_logits(logits[:, 0], seed, temperature,
                                   top_k, top_p)
    return tgt, samp0, cache_k, cache_v


@jax.jit
def sample_logits(logits, seed, temperature, top_k, top_p):
    """Standalone sampler dispatch (the chunked-prefill tail — the
    whole-prompt path fuses sampling into prefill_sample instead)."""
    from .sampling import sample_from_logits

    return sample_from_logits(logits, seed, temperature, top_k, top_p)


# --- fused step functions: model + sampler in ONE dispatch ------------------
# Over the axon relay (remote TPU) every dispatch pays a network round
# trip; fusing sampling into the step cuts per-token latency by ~the RTT.

@partial(jax.jit, static_argnames=("cfg", "greedy"),
         donate_argnames=("cache_k", "cache_v"))
def prefill_sample(params, cache_k, cache_v, tokens, prompt_lens,
                   block_tables, cos, sin, seed, temperature, top_k,
                   top_p, lora=None, *, cfg: LlamaConfig,
                   greedy: bool = False):
    """``greedy=True`` (every request temperature==0) compiles an
    argmax-only epilogue — bit-identical results for greedy requests,
    and a materially simpler program: the top_k/sort/categorical
    sampler fused behind multi-GiB weight args is the one program class
    the relay-attached TPU rejects nondeterministically (r5 bisection:
    model+argmax stable across trials, model+sort-sampler not, at
    identical HBM footprints)."""
    from .sampling import sample_from_logits

    logits, cache_k, cache_v = prefill.__wrapped__(
        params, cache_k, cache_v, tokens, prompt_lens, block_tables,
        cos, sin, lora, cfg=cfg)
    if greedy:
        toks = jnp.argmax(logits, axis=-1)
    else:
        toks = sample_from_logits(logits, seed, temperature, top_k,
                                  top_p)
    return toks, cache_k, cache_v


@partial(jax.jit,
         static_argnames=("cfg", "n_steps", "paged_kernel", "greedy"),
         donate_argnames=("cache_k", "cache_v"))
def decode_burst(params, cache_k, cache_v, tokens, positions,
                 block_tables, active, cos, sin, seed, temperature,
                 top_k, top_p, lora=None, *, cfg: LlamaConfig,
                 n_steps: int, paged_kernel: bool = None,
                 greedy: bool = False):
    """n_steps fused decode+sample steps, sampled tokens fed back
    ON-DEVICE (multi-step scheduling, vLLM's --num-scheduler-steps
    analog). One host round trip yields n_steps tokens per slot — the
    decisive win when the host⇄TPU link has real latency (axon relay),
    and it also hides per-step dispatch overhead locally.

    HBM discipline: the big cache never rides the step-scan carry (that
    would copy it every step). The burst's new KV rows accumulate in a
    [L, B, K] scratch; attention runs over (pages gathered once per
    burst) + (scratch, causally masked per step); the scratch scatters
    into the paged cache ONCE at the end. ``block_tables`` may be a
    narrowed slice of the full table — the engine buckets it to the
    longest active context, so KV read traffic scales with real context,
    not max_seq_len.

    Returns (tokens [n_steps, B], cache_k, cache_v). The host must have
    pre-provisioned pages for positions .. positions+n_steps-1.
    """
    from .sampling import sample_from_logits

    from .._private.config import global_config
    from .lora import lora_delta

    # static jit arg (None -> config default) so flag flips retrace
    use_paged_kernel = (global_config().llm_paged_kernel
                        if paged_kernel is None else paged_kernel)
    B = tokens.shape[0]
    K = n_steps
    L = cfg.n_layers
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // cfg.n_kv_heads
    page_size = cache_k.shape[2]
    Sold = block_tables.shape[1] * page_size
    if use_paged_kernel:
        # pages stream straight through the Pallas kernel per layer —
        # no materialized [L, B, Sold] gather copy in HBM
        old_k = old_v = jnp.zeros((L, 0), cache_k.dtype)
    else:
        # old context gathered ONCE per burst (read-only during burst)
        old_k = jnp.take(cache_k, block_tables, axis=1).reshape(
            L, B, Sold, kvh, hd)
        old_v = jnp.take(cache_v, block_tables, axis=1).reshape(
            L, B, Sold, kvh, hd)
    scratch_k = jnp.zeros((L, B, K, kvh, hd), cache_k.dtype)
    scratch_v = jnp.zeros((L, B, K, kvh, hd), cache_v.dtype)
    lora_xs = {} if not lora else {
        k2: jnp.swapaxes(v2, 0, 1) for k2, v2 in lora.items()
        if k2 != "scale"}
    old_mask = jnp.arange(Sold)[None, :] < positions[:, None]  # [B, Sold]

    def step(carry, i):
        toks, sk, sv = carry
        pos_i = positions + i
        x = embed_lookup(params["embed"], toks, cfg.dtype)[:, None, :]
        new_mask = jnp.arange(K)[None, :] <= i                 # [1, K]

        def attend_gathered(qg, ok, ov, nk, nv):
            # bf16 operands straight onto the MXU, f32 accumulation
            s_old = jnp.einsum("bgrd,bsgd->bgrs", qg, ok,
                               preferred_element_type=jnp.float32)
            s_new = jnp.einsum("bgrd,bkgd->bgrk", qg, nk,
                               preferred_element_type=jnp.float32)
            scale = hd ** -0.5
            s_old = jnp.where(old_mask[:, None, None, :], s_old * scale,
                              -jnp.inf)
            s_new = jnp.where(new_mask[None, None, :, :], s_new * scale,
                              -jnp.inf)
            s_all = jnp.concatenate([s_old, s_new], axis=-1)
            p_all = jax.nn.softmax(s_all, axis=-1).astype(ok.dtype)
            return (jnp.einsum("bgrs,bsgd->bgrd", p_all[..., :Sold], ov,
                               preferred_element_type=jnp.float32)
                    + jnp.einsum("bgrk,bkgd->bgrd", p_all[..., Sold:], nv,
                                 preferred_element_type=jnp.float32))

        def attend_paged(qg, ck_l, cv_l, nk, nv):
            from ..ops.paged_attention import paged_decode_attention

            return paged_decode_attention(
                qg, ck_l, cv_l, nk, nv, block_tables, positions,
                jnp.full((B,), i + 1, jnp.int32),
                page_size=page_size).astype(jnp.float32)

        def layer(x, inputs):
            lp, ok, ov, nk, nv, lr = inputs
            h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
            q = weight_einsum("bsd,dhk->bshk", h, lp["wq"])
            k = weight_einsum("bsd,dhk->bshk", h, lp["wk"])
            v = weight_einsum("bsd,dhk->bshk", h, lp["wv"])
            if lr:
                q = q + lora_delta(h, lr["a_q"], lr["b_q"],
                                   lora["scale"], cfg.n_heads, hd)
                v = v + lora_delta(h, lr["a_v"], lr["b_v"],
                                   lora["scale"], kvh, hd)
            q = apply_rotary(q, cos, sin, positions=pos_i[:, None])[:, 0]
            k = apply_rotary(k, cos, sin, positions=pos_i[:, None])[:, 0]
            nk = jax.lax.dynamic_update_index_in_dim(
                nk, k.astype(nk.dtype), i, 1)
            nv = jax.lax.dynamic_update_index_in_dim(
                nv, v[:, 0].astype(nv.dtype), i, 1)
            qg = q.reshape(B, kvh, rep, hd)
            if use_paged_kernel:
                o = attend_paged(qg, ok, ov, nk, nv)
            else:
                o = attend_gathered(qg, ok, ov, nk, nv)
            o = o.reshape(B, 1, cfg.n_heads, hd).astype(x.dtype)
            x = x + weight_einsum("bshk,hkd->bsd", o, lp["wo"])
            h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
            x = x + _mlp(h, lp, cfg)
            return x, (nk, nv)

        if use_paged_kernel:
            # UNROLLED layers: a lax.scan over the cache would dynamic-
            # slice the whole [L, P, ...] page pool per (step, layer) —
            # measured 2.6x slower than the gather path. Static slices
            # in an unrolled loop let XLA alias into the donated pool.
            sks, svs = [], []
            for li in range(L):
                lp_l = jax.tree.map(lambda a: a[li], params["layers"])
                lr_l = {k2: v2[li] for k2, v2 in lora_xs.items()}
                x, (nk_l, nv_l) = layer(
                    x, (lp_l, cache_k[li], cache_v[li], sk[li], sv[li],
                        lr_l))
                sks.append(nk_l)
                svs.append(nv_l)
            sk = jnp.stack(sks)
            sv = jnp.stack(svs)
        else:
            x, (sk, sv) = jax.lax.scan(
                layer, x, (params["layers"], old_k, old_v, sk, sv,
                           lora_xs))
        h = rms_norm(x[:, 0], params["final_norm"], cfg.norm_eps)
        logits = _lm_logits(h, params, cfg)
        if greedy:   # see prefill_sample: argmax-only epilogue
            newt = jnp.argmax(logits, axis=-1)
        else:
            newt = sample_from_logits(logits, seed + i, temperature,
                                      top_k, top_p)
        newt = jnp.where(active, newt, toks)
        return (newt, sk, sv), newt

    (_, scratch_k, scratch_v), out = jax.lax.scan(
        step, (tokens, scratch_k, scratch_v), jnp.arange(K))

    # one scatter of the whole burst into the paged cache (donated ->
    # in-place); inactive slots land on dump page 0
    p_grid = positions[:, None] + jnp.arange(K)[None, :]       # [B, K]
    page_idx = jnp.take_along_axis(block_tables, p_grid // page_size,
                                   axis=1)
    valid = active[:, None]
    page_idx = jnp.where(valid, page_idx, 0)
    offset = jnp.where(valid, p_grid % page_size, 0)
    fp, fo = page_idx.reshape(-1), offset.reshape(-1)          # [B*K]
    cache_k = cache_k.at[:, fp, fo].set(
        scratch_k.reshape(L, B * K, kvh, hd), mode="drop")
    cache_v = cache_v.at[:, fp, fo].set(
        scratch_v.reshape(L, B * K, kvh, hd), mode="drop")
    return out, cache_k, cache_v
