"""Token sampling: greedy / temperature / top-k / top-p, batched per-slot.

Reference analog: vLLM SamplingParams (the surface ray.llm exposes through
vllm_models.py). One jitted sampler runs for the whole slot batch with
per-slot parameter arrays — no retrace when requests with different
settings share a decode step.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

# static cap on per-request top_k so the lax.top_k width stays compiled-in
TOP_K_CAP = 128


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.0      # 0 => greedy
    top_k: int = 0                # 0 => disabled (full vocab)
    top_p: float = 1.0            # nucleus mass; 1.0 => disabled
    max_tokens: int = 128
    stop_token_ids: tuple = ()
    logprobs: bool = False

    def __post_init__(self):
        if self.top_k > TOP_K_CAP:
            object.__setattr__(self, "top_k", TOP_K_CAP)


def sample_from_logits(logits, seed, temperature, top_k, top_p):
    """Trace-level sampler: called inside the runner's fused
    prefill/decode jits (one device dispatch per engine step).

    logits: [B, V] f32; seed: scalar i32 (stepped by the engine each
    decode); temperature/top_p: [B] f32; top_k: [B] i32 (0 = off).
    Greedy rows (temperature == 0) ignore the PRNG entirely.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    # restrict to the TOP_K_CAP best logits once; both top-k and top-p
    # operate inside this window (exact for top_k <= cap, and nucleus
    # mass beyond the top-128 tokens is negligible for real models)
    kcap = min(TOP_K_CAP, V)
    top_vals, top_idx = jax.lax.top_k(logits, kcap)        # [B, kcap] sorted
    ranks = jnp.arange(kcap)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, kcap), kcap)
    masked = jnp.where(ranks < k_eff[:, None], top_vals, -jnp.inf)

    # temperature FIRST, nucleus second (vLLM/HF semantics: top_p is a
    # mass cut on the temperature-scaled distribution — a hot
    # distribution admits more tokens into the nucleus)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = masked / temp
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix reaching top_p (always >= 1 token)
    keep = (cum - probs) < top_p[:, None]
    scaled = jnp.where(keep, scaled, -jnp.inf)

    # one key per step: categorical draws independent gumbel noise per
    # row, so slots don't correlate
    key = jax.random.PRNGKey(seed)
    sampled_pos = jax.random.categorical(key, scaled, axis=-1)
    sampled = jnp.take_along_axis(top_idx, sampled_pos[:, None],
                                  axis=1)[:, 0]
    return jnp.where(temperature <= 0.0, greedy, sampled)
