"""Speculative decoding plane (Leviathan et al., ICML'23 — PAPERS.md):
a small drafter proposes k tokens per slot, the target model scores the
whole window in ONE batched forward (runner.verify_step), and
accept-prefix semantics emit the longest agreeing prefix plus one
corrected token — output token-for-token identical to the greedy
oracle, 1..k+1 tokens per round instead of 1.

Key invariant the engine relies on: the emitted tokens are exactly the
first m+1 tokens of the target's greedy continuation, where m is the
length of the longest draft prefix that agrees with it. ANY correct
computation of the greedy continuation therefore yields the identical
emission — which is why the fleet verifier (a prefill-class replica fed
a KV snapshot) and the corrupt-payload recompute fallback can never
diverge from the monolithic round.

Drafter cache discipline: the drafter keeps its OWN paged KV cache but
mirrors the target's block tables (same page ids, no second allocator —
both caches are [layers, num_pages, ...]); shared prefix pages hold
token-identical content in both, so prefix-cache page sharing stays
sound. Each draft round opens with a 2-token repair window [p-1, p]:
after a full accept + bonus, the previous round's last draft token
never ran through the drafter, so the drafter KV can trail the target
by AT MOST one position — which the repair window always rewrites.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..models.llama import LLAMA_CONFIGS, LlamaConfig
from ..ops import rope_frequencies
from .cache import KVCache, init_kv_cache
from .runner import decode_burst, prefill_bucket, verify_step
from .runner import prefill as _runner_prefill

import jax.numpy as jnp


def accept_prefix(draft: Sequence[int], target: Sequence[int]) -> List[int]:
    """Greedy accept-prefix: ``target[j]`` is the target's argmax AFTER
    consuming window position j (the token preceding ``draft[j]``), so
    ``draft[j]`` is accepted iff it equals ``target[j]``. Returns the
    accepted prefix plus ``target[m]`` — the correction on the first
    disagreement, or the free bonus token on a full accept. Always emits
    at least one token; ``len(target)`` must exceed ``len(draft)``."""
    m = 0
    for j, d in enumerate(draft):
        if int(d) != int(target[j]):
            break
        m += 1
    return [int(t) for t in draft[:m]] + [int(target[m])]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """``speculation={"draft_config": ..., "num_draft_tokens": k}`` as it
    arrives from serve.deployment / YAML. ``draft_config`` names an
    LLAMA_CONFIGS entry (or is a LlamaConfig); ``draft_seed`` seeds the
    drafter's random init when no params are supplied."""
    draft_config: Any
    num_draft_tokens: int = 3
    draft_seed: int = 0

    @classmethod
    def parse(cls, obj: Any) -> "SpecConfig":
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            known = {"draft_config", "num_draft_tokens", "draft_seed"}
            extra = sorted(set(obj) - known)
            if extra:
                raise ValueError(f"unknown speculation keys {extra}; "
                                 f"expected a subset of {sorted(known)}")
            if "draft_config" not in obj:
                raise ValueError("speculation requires 'draft_config'")
            return cls(draft_config=obj["draft_config"],
                       num_draft_tokens=int(obj.get("num_draft_tokens", 3)),
                       draft_seed=int(obj.get("draft_seed", 0)))
        raise TypeError(f"speculation must be a dict or SpecConfig, "
                        f"got {type(obj).__name__}")


def _resolve_draft_cfg(dc: Any) -> LlamaConfig:
    if isinstance(dc, LlamaConfig):
        return dc
    if isinstance(dc, str):
        try:
            return LLAMA_CONFIGS[dc]
        except KeyError:
            raise ValueError(
                f"unknown draft_config {dc!r}; known: "
                f"{sorted(LLAMA_CONFIGS)}") from None
    raise TypeError(f"draft_config must be a name or LlamaConfig, "
                    f"got {type(dc).__name__}")


class SpecDecoder:
    """Drafter half of the spec-decode plane: owns the draft model's
    params + paged KV cache and proposes k tokens per drafted slot. The
    engine owns scheduling, verification and emission."""

    def __init__(self, target_cfg: LlamaConfig, ecfg, spec_cfg,
                 draft_params=None):
        sc = SpecConfig.parse(spec_cfg)
        if sc.num_draft_tokens < 1:
            raise ValueError("num_draft_tokens must be >= 1")
        dcfg = _resolve_draft_cfg(sc.draft_config)
        if ecfg.max_seq_len > dcfg.max_seq:
            raise ValueError(
                f"draft model max_seq {dcfg.max_seq} < engine "
                f"max_seq_len {ecfg.max_seq_len}")
        if dcfg.vocab < target_cfg.vocab:
            # still CORRECT (the drafter just can never propose ids >=
            # its vocab, so those positions always reject) but almost
            # certainly a tokenizer mismatch — refuse loudly
            raise ValueError(
                f"draft vocab {dcfg.vocab} < target vocab "
                f"{target_cfg.vocab}: drafter cannot propose every "
                f"target token")
        self.spec_cfg = sc
        self.dcfg = dcfg
        self.ecfg = ecfg
        self.k = sc.num_draft_tokens
        if draft_params is None:
            from ..models.llama import init_params

            draft_params = init_params(
                jax.random.PRNGKey(sc.draft_seed), dcfg)
        self.params = draft_params
        # mirrors the target's page pool 1:1 — block tables are shared
        self.cache = init_kv_cache(dcfg, ecfg.num_pages, ecfg.page_size,
                                   None)
        cos, sin = rope_frequencies(dcfg.head_dim, dcfg.max_seq,
                                    dcfg.rope_theta)
        self.cos, self.sin = jax.device_put(cos), jax.device_put(sin)
        # slots whose draft cache currently covers their sequence; a
        # drop() (preempt/finish/handoff) forces a fresh warm-up prefill
        self.ready: set = set()
        # counters, drained by the serve metrics pump
        self.drafted_total = 0
        self.accepted_total = 0
        self.emitted_total = 0
        self.rounds_total = 0
        self.remote_rounds_total = 0
        self.remote_agree_total = 0
        self.verify_times: List[float] = []

    # --- bookkeeping ---

    def drop(self, slot: int) -> None:
        self.ready.discard(slot)

    def reset(self) -> None:
        self.ready.clear()

    def on_round(self, drafted: int, accepted: int) -> None:
        self.drafted_total += drafted
        self.accepted_total += accepted
        self.emitted_total += accepted + 1
        self.rounds_total += 1

    @property
    def acceptance_ratio(self) -> float:
        return (self.accepted_total / self.drafted_total
                if self.drafted_total else 0.0)

    def take_verify_times(self) -> List[float]:
        out, self.verify_times = self.verify_times, []
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "draft_tokens": self.drafted_total,
            "accepted_tokens": self.accepted_total,
            "rounds": self.rounds_total,
            "acceptance_ratio": self.acceptance_ratio,
            "remote_rounds": self.remote_rounds_total,
            "remote_agree": self.remote_agree_total,
        }

    # --- device work ---

    def prefill(self, tokens: Sequence[int], block_row) -> None:
        """Warm the drafter KV for positions [0, len(tokens)) of one
        slot (first drafted round, or resume after drop). ``block_row``
        is the slot's [1, max_pages] block-table row."""
        L = len(tokens)
        bucket = prefill_bucket(L, self.ecfg.max_seq_len)
        tok = np.zeros((1, bucket), np.int32)
        tok[0, :L] = tokens
        _logits, ck, cv = _runner_prefill(
            self.params, self.cache.k, self.cache.v, jnp.asarray(tok),
            jnp.asarray([L], jnp.int32), jnp.asarray(block_row),
            self.cos, self.sin, None, cfg=self.dcfg)
        self.cache = KVCache(ck, cv)

    def draft(self, items: Sequence[Tuple[int, int, int, int]],
              bt) -> Dict[int, List[int]]:
        """Propose k tokens per drafted slot. ``items`` rows are
        ``(slot, token_at_p_minus_1, token_at_p, p)`` with p the slot's
        ctx_len; ``bt`` is the device block table [B, span] shared with
        the target. The 2-token repair window [p-1, p] rewrites the at
        most one drafter-KV position the previous round's bonus token
        skipped and yields d_1; a greedy decode burst continues
        d_2..d_k. Returns {slot: [d_1 .. d_k]}."""
        B = int(bt.shape[0])
        tok2 = np.zeros((B, 2), np.int32)
        pos2 = np.full((B, 2), -1, np.int32)
        pos1 = np.zeros(B, np.int32)
        active = np.zeros(B, bool)
        for slot, t_prev, t_last, p in items:
            tok2[slot] = (t_prev, t_last)
            pos2[slot] = (p - 1, p)
            pos1[slot] = p + 1
            active[slot] = True
        zf = jnp.zeros((B,), jnp.float32)
        zi = jnp.zeros((B,), jnp.int32)
        of = jnp.ones((B,), jnp.float32)
        tgt2, _s0, ck, cv = verify_step(
            self.params, self.cache.k, self.cache.v, jnp.asarray(tok2),
            jnp.asarray(pos2), bt, self.cos, self.sin, 0, zf, zi, of,
            cfg=self.dcfg, greedy=True)
        d1 = tgt2[:, 1]
        if self.k > 1:
            toks, ck, cv = decode_burst(
                self.params, ck, cv, d1, jnp.asarray(pos1), bt,
                jnp.asarray(active), self.cos, self.sin, 0, of, zi, of,
                None, cfg=self.dcfg, n_steps=self.k - 1,
                paged_kernel=False, greedy=True)
            rest = np.asarray(toks)                        # [k-1, B]
        else:
            rest = np.zeros((0, B), np.int32)
        self.cache = KVCache(ck, cv)
        d1 = np.asarray(d1)
        out: Dict[int, List[int]] = {}
        for slot, _tp, _tl, _p in items:
            out[slot] = [int(d1[slot])] + [int(rest[j, slot])
                                           for j in range(self.k - 1)]
        return out


def remote_verify(engine, payload: Dict[str, Any],
                  draft: Sequence[int],
                  params=None) -> List[int]:
    """Fleet verifier entry point: inject a KV snapshot into ``engine``
    (a scratch verifier on a prefill-class replica), run ONE
    verification round against ``draft`` and return the emission —
    identical to the monolithic round by the greedy-continuation
    equivalence. A corrupt/unusable payload falls back to local
    recompute: the prefill pass itself emits the first greedy token,
    which consumes (or corrects) the first draft token, and the rest of
    the window verifies normally. The scratch request is aborted before
    returning, so repeated calls never accumulate state."""
    from .sampling import SamplingParams

    draft = [int(t) for t in draft]
    pre = [int(t) for t in payload.get("output") or ()]
    if params is None:
        # generous budget: the emission is clipped by the CALLER's real
        # request, never by the scratch verifier
        params = SamplingParams(
            temperature=0.0, max_tokens=len(pre) + len(draft) + 4)
    rid = engine.inject_request(payload, params=params)
    state = engine.requests[rid]
    try:
        emitted: List[int] = []
        if state.ctx_len <= 0 and not state.finished:
            # recompute fallback: drive admission+prefill only; the
            # prefill epilogue samples exactly one greedy token
            guard = 0
            limit = 4 * (len(payload.get("prompt") or ()) + len(pre) + 8)
            while not state.finished and state.ctx_len <= 0:
                engine.step(skip_decode=True)
                guard += 1
                if guard > limit:
                    raise RuntimeError(
                        f"recompute fallback for {rid} made no progress")
            fresh = [int(t) for t in state.output[len(pre):]]
            for t in fresh:
                emitted.append(t)
                if draft and draft[0] == t:
                    draft.pop(0)
                else:
                    return emitted       # correction: round is over
        if state.finished:
            return emitted
        emitted.extend(engine.verify_request(rid, draft))
        return emitted
    finally:
        engine.abort_request(rid)
