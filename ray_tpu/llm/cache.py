"""Paged KV cache: device arrays + host-side page allocator.

Reference analog: the vLLM engine the reference wraps for LLM serving
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py) keeps
its paged KV cache in CUDA; here the cache is a pair of jax arrays of
STATIC shape [layers, pages, page_size, kv_heads, head_dim] living in HBM
— XLA-friendly (no dynamic allocation inside jit) with all paging
decisions made host-side by a free-list allocator.

Page 0 is reserved as the *dump page*: padded scatter lanes write there so
the jitted kernels never branch on validity; it is never handed out.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KVCache:
    """Device-side paged cache (one pair of stacked-layer arrays)."""

    k: jax.Array  # [L, num_pages, page_size, kv_heads, head_dim]
    v: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def init_kv_cache(cfg, num_pages: int, page_size: int,
                  dtype=None) -> KVCache:
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads,
             cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


class PageAllocator:
    """Host-side free list over the cache's page pool (page 0 reserved).

    Pages are REFERENCE-COUNTED: prefix caching shares prompt pages
    across sequences (vLLM's automatic-prefix-caching page sharing), so
    ``free`` decrements and only a zero count returns the page to the
    free list."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.page_size = page_size
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: dict = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def can_allocate(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def allocate(self, n_pages: int) -> List[int]:
        if n_pages > len(self._free):
            raise MemoryError(
                f"KV cache out of pages: want {n_pages}, "
                f"free {len(self._free)}")
        out = [self._free.pop() for _ in range(n_pages)]
        for p in out:
            self._refs[p] = 1
        return out

    def incref(self, page: int) -> None:
        if page not in self._refs:
            raise ValueError(f"incref on unallocated page {page}")
        self._refs[page] += 1

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"freeing invalid page {p}")
            if p not in self._refs:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


class PrefixCache:
    """Content-addressed full prompt pages (ref: vLLM automatic prefix
    caching — --enable-prefix-caching). A page's key is the hash chain
    (parent key, the page's token ids), so a lookup walks the prompt's
    full pages and reuses the longest cached chain; reused pages are
    shared via the allocator's refcounts and their KV is NOT recomputed
    (chunked prefill starts past them). The cache holds one reference
    per cached page; eviction (LRU) releases it."""

    def __init__(self, allocator: PageAllocator):
        self._alloc = allocator
        self._pages: Dict[Any, int] = {}      # key -> page index
        self._lru: "OrderedDict[Any, None]" = OrderedDict()
        self._parent: Dict[Any, Any] = {}     # key -> parent key (0=root)
        self._children: Dict[Any, int] = {}   # cached children per key

    @staticmethod
    def page_keys(prompt, page_size: int) -> List[Any]:
        """Keys for each FULL page of the prompt (chained).

        SHA-256 over (parent digest + the page's token bytes), NOT the
        builtin hash(): these keys route one request's cached KV pages
        to other prompts, so a 64-bit (and PYTHONHASHSEED-dependent)
        hash collision silently serves a DIFFERENT prompt's KV — the
        same class of cross-request leak as vLLM's prefix-cache hash
        fix. Tokens pack as fixed-width int64 so no two token sequences
        share an encoding.

        The chain itself lives in serve/kv_router.py (stdlib-only, so
        handles/proxies can derive it without importing jax); this
        delegates so engines and routers can never drift apart."""
        from ..serve.kv_router import chained_page_keys

        return chained_page_keys(prompt, page_size)

    def __len__(self) -> int:
        return len(self._pages)

    def lookup(self, keys: List[Any]) -> List[int]:
        """Longest cached prefix chain: pages for keys[0..k), each
        increffed for the caller."""
        out: List[int] = []
        for key in keys:
            page = self._pages.get(key)
            if page is None:
                break
            self._alloc.incref(page)
            self._lru.move_to_end(key)
            out.append(page)
        return out

    def insert(self, keys: List[Any], pages: List[int]) -> None:
        """Register freshly-filled prompt pages; the cache takes one
        reference per NEW entry (a key already present keeps the
        existing page — identical content)."""
        parent = 0
        for key, page in zip(keys, pages):
            if key in self._pages:
                parent = key
                continue
            self._alloc.incref(page)
            self._pages[key] = page
            self._lru[key] = None
            self._parent[key] = parent
            if parent:
                self._children[parent] = self._children.get(parent, 0) + 1
            parent = key

    def evictable(self) -> int:
        """Pages only the cache holds (the reclaimable set)."""
        return sum(1 for p in self._pages.values()
                   if self._alloc.refcount(p) == 1)

    def evict(self, n_pages: int) -> int:
        """Release up to n_pages cache-only pages, LEAF pages first (a
        chain's root evicted first would strand its whole tail
        unreachable — lookups break at the first miss; vLLM evicts leaf
        blocks first for the same reason), LRU-ordered within leaves.
        Returns pages released."""
        released = 0
        progress = True
        while released < n_pages and progress:
            progress = False
            for key in list(self._lru):
                if released >= n_pages:
                    break
                if self._children.get(key, 0):
                    continue   # interior node: evict its leaves first
                page = self._pages[key]
                if self._alloc.refcount(page) != 1:
                    continue   # a live sequence still shares it
                self._alloc.free([page])
                del self._pages[key]
                del self._lru[key]
                parent = self._parent.pop(key, 0)
                if parent and parent in self._children:
                    self._children[parent] -= 1
                    if not self._children[parent]:
                        del self._children[parent]
                self._children.pop(key, None)
                released += 1
                progress = True
        return released

    def evict_for(self, n_tokens: int) -> None:
        """Evict until the allocator can serve n_tokens (best effort)."""
        while not self._alloc.can_allocate(n_tokens):
            if not self.evict(1):
                return


class SequenceTable:
    """Per-sequence page bookkeeping: block table rows handed to the
    jitted kernels (numpy host-side; copied to device per step)."""

    def __init__(self, max_seqs: int, max_pages_per_seq: int):
        self.block_tables = np.zeros((max_seqs, max_pages_per_seq), np.int32)
        self.n_pages = np.zeros(max_seqs, np.int32)
        # bumped on every mutation so the engine can cache the device copy
        self.version = 0

    def assign(self, slot: int, pages: List[int]) -> None:
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.n_pages[slot] = len(pages)
        self.version += 1

    def append_page(self, slot: int, page: int) -> None:
        idx = int(self.n_pages[slot])
        if idx >= self.block_tables.shape[1]:
            raise MemoryError(f"slot {slot}: sequence exceeds "
                              f"max_pages_per_seq={self.block_tables.shape[1]}")
        self.block_tables[slot, idx] = page
        self.n_pages[slot] = idx + 1
        self.version += 1

    def pages_of(self, slot: int) -> List[int]:
        return [int(p) for p in
                self.block_tables[slot, :int(self.n_pages[slot])]]

    def clear(self, slot: int) -> None:
        self.block_tables[slot, :] = 0
        self.n_pages[slot] = 0
        self.version += 1
