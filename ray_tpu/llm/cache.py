"""Paged KV cache: device arrays + host-side page allocator.

Reference analog: the vLLM engine the reference wraps for LLM serving
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py) keeps
its paged KV cache in CUDA; here the cache is a pair of jax arrays of
STATIC shape [layers, pages, page_size, kv_heads, head_dim] living in HBM
— XLA-friendly (no dynamic allocation inside jit) with all paging
decisions made host-side by a free-list allocator.

Page 0 is reserved as the *dump page*: padded scatter lanes write there so
the jitted kernels never branch on validity; it is never handed out.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KVCache:
    """Device-side paged cache (one pair of stacked-layer arrays)."""

    k: jax.Array  # [L, num_pages, page_size, kv_heads, head_dim]
    v: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def init_kv_cache(cfg, num_pages: int, page_size: int,
                  dtype=None) -> KVCache:
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads,
             cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


class PageAllocator:
    """Host-side free list over the cache's page pool (page 0 reserved)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.page_size = page_size
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def can_allocate(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def allocate(self, n_pages: int) -> List[int]:
        if n_pages > len(self._free):
            raise MemoryError(
                f"KV cache out of pages: want {n_pages}, "
                f"free {len(self._free)}")
        out = [self._free.pop() for _ in range(n_pages)]
        return out

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"freeing invalid page {p}")
        self._free.extend(pages)


class SequenceTable:
    """Per-sequence page bookkeeping: block table rows handed to the
    jitted kernels (numpy host-side; copied to device per step)."""

    def __init__(self, max_seqs: int, max_pages_per_seq: int):
        self.block_tables = np.zeros((max_seqs, max_pages_per_seq), np.int32)
        self.n_pages = np.zeros(max_seqs, np.int32)
        # bumped on every mutation so the engine can cache the device copy
        self.version = 0

    def assign(self, slot: int, pages: List[int]) -> None:
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :len(pages)] = pages
        self.n_pages[slot] = len(pages)
        self.version += 1

    def append_page(self, slot: int, page: int) -> None:
        idx = int(self.n_pages[slot])
        if idx >= self.block_tables.shape[1]:
            raise MemoryError(f"slot {slot}: sequence exceeds "
                              f"max_pages_per_seq={self.block_tables.shape[1]}")
        self.block_tables[slot, idx] = page
        self.n_pages[slot] = idx + 1
        self.version += 1

    def pages_of(self, slot: int) -> List[int]:
        return [int(p) for p in
                self.block_tables[slot, :int(self.n_pages[slot])]]

    def clear(self, slot: int) -> None:
        self.block_tables[slot, :] = 0
        self.n_pages[slot] = 0
        self.version += 1
