"""LLM serving deployment: the engine behind an async serve replica.

Reference analog: ray.llm's serve deployments
(llm/_internal/serve/deployments/llm/llm_server.py wrapping vLLM's async
engine, + the OpenAI router in _internal/serve/deployments/routers/).
Here the continuous-batching engine runs on a replica-side thread; each
request registers an asyncio queue that the engine pump feeds, so many
HTTP streams multiplex over ONE decode batch — the continuous-batching
payoff serve exists to deliver.

Usage:
    app = build_llm_deployment("tiny", init="random")   # or params blob
    handle = serve.run(app)
    out = await handle.completions.remote({"prompt_ids": [...]})
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Dict, List, Optional

from ..models.llama import LLAMA_CONFIGS, LlamaConfig, init_params
from .engine import EngineConfig, LLMEngine
from .sampling import SamplingParams


class LLMServer:
    """Serve deployment class hosting one engine replica."""

    def __init__(self, model: str = "tiny", *, init: str = "random",
                 params_path: Optional[str] = None,
                 engine_config: Optional[dict] = None,
                 tokenizer: Optional[str] = None, seed: int = 0,
                 quantize: Optional[str] = None,
                 speculation: Optional[dict] = None):
        import jax

        self.model_name = model
        if model in LLAMA_CONFIGS:
            cfg = LLAMA_CONFIGS[model]
        elif os.path.isdir(model):
            cfg = None  # an HF checkpoint directory IS the model source
        else:
            raise ValueError(f"unknown model {model!r}: not a named "
                             f"config or an HF checkpoint dir")
        if cfg is None or init == "hf":
            # real weights: HF safetensors directory (hf_interop.py) —
            # the vLLM-engine weight-loading analog
            from ..models.hf_interop import load_hf_checkpoint

            path = model if cfg is None else (params_path or model)
            if not os.path.isdir(path):
                raise ValueError(
                    f"init='hf' needs an HF checkpoint directory; "
                    f"{path!r} is not one (pass it as `model` or "
                    f"`params_path`)")
            # quantize="int8": host-side per-channel int8 before the
            # device sees anything — how Llama-3-8B serves on one 16 GB
            # chip (ops/quant.py)
            params, cfg = load_hf_checkpoint(path, quantize=quantize)
            params = jax.device_put(params)
            if tokenizer is None and os.path.exists(
                    os.path.join(path, "tokenizer_config.json")):
                tokenizer = path
        elif params_path:
            import pickle

            if quantize is not None:
                raise ValueError(
                    "quantize applies to HF-checkpoint loading only "
                    "(init='hf' / a checkpoint-dir model)")
            with open(params_path, "rb") as f:
                params = pickle.load(f)
            params = jax.device_put(params)
        elif init == "random":
            if quantize is not None:
                raise ValueError(
                    "quantize applies to HF-checkpoint loading only "
                    "(init='hf' / a checkpoint-dir model)")
            params = init_params(jax.random.PRNGKey(seed), cfg)
        else:
            raise ValueError(f"unknown init {init!r}")
        ecfg = EngineConfig(**(engine_config or {}))
        self.engine = LLMEngine(params, cfg, ecfg)
        self.tokenizer = None
        if tokenizer:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(tokenizer)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._pump_task: Optional[asyncio.Task] = None
        # per-tenant accounting: request id -> tenant, stashed at submit
        # (the serve tenant contextvar is gone by the time the pump
        # thread observes the finished request) and popped on finish
        self._tenants: Dict[str, str] = {}
        # fleet KV plane (disaggregated serving): pool role, set by the
        # replica's configure_pool hook before any request lands.
        # "mono" = classic all-in-one replica; "prefill" runs prompt
        # passes only and ships KV to the decode pool; "decode" accepts
        # injected KV and runs decode only.
        self._pool = "mono"
        self._dep_name: Optional[str] = None
        self._decode_handle = None
        self._m_handoff_bytes = None
        self._m_handoff_lat = None
        self._m_handoff_retries = None
        self._last_summary = None
        # serializes engine mutation between the pump's executor thread
        # and loop-side KV export/inject
        import threading

        self._engine_lock = threading.Lock()
        # serving metrics (ref: vLLM's engine stat logger — TTFT/TPOT
        # histograms, scheduler-state and cache-hit gauges), exported
        # through the util.metrics -> GCS -> /metrics pipeline. The
        # "pool" tag splits TTFT/TPOT by replica role so disaggregated
        # deployments meter prefill and decode separately.
        from ..util import metrics

        tags = {"model": self.model_name, "pool": self._pool}
        self._m_ttft = metrics.Histogram(
            "llm_ttft_seconds", "Time to first token per request",
            boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        self._m_tpot = metrics.Histogram(
            "llm_tpot_seconds", "Time per output token (decode) "
            "per request", boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        self._m_e2e = metrics.Histogram(
            "llm_request_e2e_seconds", "Arrival-to-finish request latency",
            boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        self._m_queue = metrics.Gauge(
            "llm_queue_depth", "Requests waiting for a decode slot",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_occupancy = metrics.Gauge(
            "llm_batch_slot_occupancy",
            "Fraction of decode slots running (continuous batching)",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_kv_util = metrics.Gauge(
            "llm_kv_page_utilization", "Fraction of KV-cache pages in use",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_cache_hit = metrics.Counter(
            "llm_prefix_cache_hit_tokens_total",
            "Prompt tokens served from the prefix cache",
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        self._m_prompt = metrics.Counter(
            "llm_prompt_tokens_total", "Prompt tokens received",
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        self._m_generated = metrics.Counter(
            "llm_generation_tokens_total", "Tokens generated",
            tag_keys=("model", "pool", "tenant")).set_default_tags(tags)
        # speculative decoding (llm/spec_decode.py): per-round counters
        # drained from the engine's SpecDecoder by the pump. The
        # acceptance ratio is THE health signal — a drafter that stops
        # agreeing with the target turns every verify into one-token
        # decode plus wasted draft FLOPs.
        self._m_spec_drafted = metrics.Counter(
            "llm_spec_draft_tokens_total",
            "Draft tokens proposed by the speculation drafter",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_spec_accepted = metrics.Counter(
            "llm_spec_accepted_tokens_total",
            "Draft tokens accepted by target verification",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_spec_ratio = metrics.Gauge(
            "llm_spec_acceptance_ratio",
            "Cumulative accepted/drafted token ratio",
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._m_spec_verify = metrics.Histogram(
            "llm_spec_verify_seconds",
            "Target-model batched verify forward latency",
            boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model", "pool")).set_default_tags(tags)
        self._spec_seen = {"drafted": 0, "accepted": 0}
        self._verify_handle = None
        if speculation:
            self.configure_speculation(speculation)

    # --- serve replica hooks (fleet KV plane) ---

    def configure_pool(self, pool: Optional[str],
                       deployment_name: str) -> None:
        """Replica hook: learn this replica's role in a disaggregated
        deployment. Prefill replicas skip decode in their pump and ship
        finished prompt KV to the decode pool; metrics re-tag so
        TTFT/TPOT split by pool."""
        self._pool = pool or "mono"
        self._dep_name = deployment_name
        tags = {"model": self.model_name, "pool": self._pool}
        for m in (self._m_ttft, self._m_tpot, self._m_e2e, self._m_queue,
                  self._m_occupancy, self._m_kv_util, self._m_cache_hit,
                  self._m_prompt, self._m_generated, self._m_spec_drafted,
                  self._m_spec_accepted, self._m_spec_ratio,
                  self._m_spec_verify):
            m.set_default_tags(tags)
        if pool == "decode":
            self._configure_fleet_verify(deployment_name)
        if pool == "prefill":
            from ..serve.handle import DeploymentHandle
            from ..util import metrics

            self._decode_handle = DeploymentHandle(
                deployment_name, "decode_from_kv", pool="decode")
            mtags = {"model": self.model_name}
            self._m_handoff_bytes = metrics.Counter(
                "serve_kv_handoff_bytes_total",
                "KV page bytes shipped prefill->decode",
                tag_keys=("model",)).set_default_tags(mtags)
            self._m_handoff_lat = metrics.Histogram(
                "serve_kv_handoff_seconds",
                "Prefill->decode KV handoff latency (export+ship+reply)",
                boundaries=metrics.LATENCY_BUCKETS,
                tag_keys=("model",)).set_default_tags(mtags)
            self._m_handoff_retries = metrics.Counter(
                "serve_kv_handoff_retries_total",
                "KV handoffs retried against another decode replica",
                tag_keys=("model",)).set_default_tags(mtags)

    def prefix_cache_summary(self):
        """Replica hook: publish this engine's cached prefix pages for
        the fleet KV router (serve/kv_router.py). None when prefix
        caching is off — the controller then stops polling this
        deployment version entirely.

        Never blocks on the engine lock: a step can hold it for seconds
        (jit compile), and waiting here would stall the replica's whole
        event loop and time out the controller's gossip probe. When the
        engine is mid-step, the previous snapshot goes out instead —
        routing hints tolerate a tick of staleness by design."""
        cache = self.engine.prefix_cache
        if cache is None:
            return None
        from ..serve import kv_router

        if self._engine_lock.acquire(blocking=False):
            try:
                keys = list(cache._pages.keys())
            finally:
                self._engine_lock.release()
            self._last_summary = kv_router.make_summary(
                keys, self.engine.ecfg.page_size)
        if self._last_summary is None:
            # first poll raced a step: publish an empty summary, NOT
            # None — None means "no hook" and stops gossip for good
            return kv_router.make_summary(
                (), self.engine.ecfg.page_size)
        return self._last_summary

    # --- speculative decoding (llm/spec_decode.py) ---

    def configure_speculation(self, spec) -> None:
        """Enable draft/verify speculative decoding on this replica's
        engine. Reached two ways: the LLMServer ``speculation`` kwarg
        (build_llm_deployment) and the serve deployment-config override
        (the Replica hook), so YAML deploys can toggle it without
        re-pickling init args."""
        if not spec:
            return
        with self._engine_lock:
            self.engine.enable_speculation(spec)

    def _configure_fleet_verify(self, deployment_name: str) -> None:
        """Decode-pool replica in fleet-verify mode: drafting happens
        here (decode chips idle between target forwards); the prefill
        pool batch-verifies each drafted window against a KV snapshot
        shipped through the object store. The local verify stays
        authoritative — the remote result corroborates it (agreement
        counters on the engine's SpecDecoder), so a lagging or dead
        prefill pool can never wrong or wedge a decode round."""
        from .._private.config import global_config

        if self.engine.spec is None \
                or not global_config().llm_spec_fleet_verify:
            return
        from ..serve.handle import DeploymentHandle

        self._verify_handle = DeploymentHandle(
            deployment_name, "verify_draft", pool="prefill")

        def _fleet_verify(payload, draft):
            # runs on the pump's executor thread inside the engine's
            # spec round: bounded by the fleet-verify timeout so a slow
            # prefill pool degrades to local-only, never a stall
            from .. import get, put

            k = payload.pop("k")
            v = payload.pop("v")
            ref = put((k, v))
            out_ref, _replica = self._verify_handle.route(
                {"handoff": payload, "kv_ref": ref,
                 "draft": [int(t) for t in draft]})
            out = get(out_ref,
                      timeout=global_config().llm_spec_fleet_verify_timeout_s)
            return None if out is None else [int(t) for t in out]

        self.engine._spec_remote_verify = _fleet_verify

    async def verify_draft(self, payload: Dict[str, Any]):
        """Prefill-pool (or any) replica endpoint: verify one drafted
        window against this replica's target weights. The KV snapshot
        rides the object store; an unusable snapshot falls back to
        recomputing the prefix inside remote_verify — slower, never
        wrong. Returns the emitted tokens (accepted prefix + the
        target's correction/bonus token)."""
        from .. import get
        from .spec_decode import remote_verify

        loop = asyncio.get_event_loop()
        meta = dict(payload["handoff"])
        if payload.get("kv_ref") is not None:
            k, v = await loop.run_in_executor(
                None, lambda: get(payload["kv_ref"], timeout=30))
            meta["k"] = k
            meta["v"] = v
        draft = [int(t) for t in payload["draft"]]

        def _run():
            with self._engine_lock:
                return remote_verify(self.engine, meta, draft)

        return await loop.run_in_executor(None, _run)

    def _drain_spec_stats(self) -> None:
        """Fold the engine SpecDecoder's cumulative counters into the
        serve metrics as deltas (the pump calls this every step)."""
        spec = self.engine.spec
        if spec is None:
            return
        d = spec.drafted_total - self._spec_seen["drafted"]
        a = spec.accepted_total - self._spec_seen["accepted"]
        if d:
            self._m_spec_drafted.inc(d)
            self._spec_seen["drafted"] = spec.drafted_total
        if a:
            self._m_spec_accepted.inc(a)
            self._spec_seen["accepted"] = spec.accepted_total
        if spec.drafted_total:
            self._m_spec_ratio.set(spec.acceptance_ratio)
        for t in spec.take_verify_times():
            self._m_spec_verify.observe(t)

    # --- engine pump: one thread-hop per step, fan-out to request queues ---

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_event_loop().create_task(
                self._pump())

    def _step_engine(self):
        # prefill replicas never decode: exported requests finish with
        # the handoff, so decode slots would only ever idle-spin
        with self._engine_lock:
            return self.engine.step(
                skip_decode=(self._pool == "prefill"))

    async def _pump(self) -> None:
        import time

        loop = asyncio.get_event_loop()
        while self.engine.has_unfinished():
            outs = await loop.run_in_executor(None, self._step_engine)
            for out in outs:
                q = self._queues.get(out.request_id)
                if q is not None:
                    q.put_nowait(out)
                if out.finished:
                    # the reader holds its queue reference; drop ours and
                    # the engine's state so a long-lived replica doesn't
                    # accumulate every past request
                    self._queues.pop(out.request_id, None)
                    state = self.engine.requests.pop(out.request_id, None)
                    if state is not None:
                        self._observe_finished(state,
                                               time.perf_counter())
            stats = self.engine.stats()
            self._drain_spec_stats()
            self._m_queue.set(stats["waiting"])
            self._m_occupancy.set(
                stats["running"] / max(1, self.engine.ecfg.max_num_seqs))
            self._m_kv_util.set(
                1.0 - stats["free_pages"] / max(1, stats["total_pages"]))
            if not outs:
                await asyncio.sleep(0.002)

    def _observe_finished(self, state, now: float) -> None:
        """Fold one finished request into the latency histograms.
        Timestamps are engine-side perf_counter marks (RequestState
        arrival_t / first_token_t), so TTFT includes queueing."""
        tags = {}
        if state.model_id:
            tags["model"] = state.model_id
        tenant = self._tenants.pop(state.request_id, None)
        if tenant:
            tags["tenant"] = tenant
        tags = tags or None
        n_out = len(state.output)
        if state.first_token_t:
            self._m_ttft.observe(state.first_token_t - state.arrival_t,
                                 tags)
            if n_out > 1:
                self._m_tpot.observe(
                    (now - state.first_token_t) / (n_out - 1), tags)
        self._m_e2e.observe(now - state.arrival_t, tags)
        if state.cached_tokens:
            self._m_cache_hit.inc(state.cached_tokens, tags)
        self._m_prompt.inc(len(state.prompt), tags)
        if n_out:
            self._m_generated.inc(n_out, tags)

    async def _submit(self, prompt_ids: List[int],
                      params: SamplingParams,
                      model_id: Optional[str] = None):
        from ..serve.replica import current_request_id, current_tenant_id

        rid_in = current_request_id()
        if rid_in and (rid_in in self._queues
                       or rid_in in self.engine.requests):
            rid_in = None  # client reused an id mid-flight: don't collide
        rid = self.engine.add_request(prompt_ids, params,
                                      request_id=rid_in,
                                      model_id=model_id)
        tenant = current_tenant_id()
        if tenant:
            self._tenants[rid] = tenant
        q: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = q
        self._ensure_pump()
        return rid, q

    def _parse(self, payload: Dict[str, Any]):
        if "prompt_ids" in payload:
            prompt_ids = [int(t) for t in payload["prompt_ids"]]
        elif "prompt" in payload and self.tokenizer is not None:
            prompt_ids = self.tokenizer.encode(payload["prompt"])
        else:
            raise ValueError(
                "need 'prompt_ids' (or 'prompt' with a tokenizer configured)")
        params = SamplingParams(
            temperature=float(payload.get("temperature", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            max_tokens=int(payload.get("max_tokens", 64)),
            stop_token_ids=tuple(payload.get("stop_token_ids", ())))
        # OpenAI-style per-request "model": the server's base-model
        # name rides the base weights; any OTHER name must be a LOADED
        # LoRA adapter — an unknown name is a client error, not a
        # silent base-model fallback
        model_id = payload.get("model")
        if model_id is not None:
            if not isinstance(model_id, str):
                raise ValueError("'model' must be a string")
            if model_id in (self.model_name, "base", ""):
                model_id = None
            elif self.engine.lora_pool is None \
                    or model_id not in self.engine.lora_pool:
                loaded = (sorted(self.engine.lora_pool._slots)
                          if self.engine.lora_pool is not None else [])
                raise ValueError(
                    f"unknown model {model_id!r}: not this server's "
                    f"base model ({self.model_name!r}) or a loaded "
                    f"LoRA adapter ({loaded})")
        return prompt_ids, params, model_id

    def _detok(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(token_ids)

    # --- API methods (serve routes by method name; HTTP hits __call__) ---

    async def __call__(self, payload: Dict[str, Any]):
        """HTTP entry: chat if 'messages' present, else completions."""
        if isinstance(payload, dict) and "messages" in payload:
            return await self.chat(payload)
        return await self.completions(payload or {})

    async def completions(self, payload: Dict[str, Any]):
        """OpenAI-completions-shaped endpoint (ref: ray.llm's OpenAI
        router). ``stream=True`` returns an async generator serve turns
        into chunked HTTP (SSE-style ``data:`` lines)."""
        prompt_ids, params, model_id = self._parse(payload)
        if self._pool == "prefill" and self._decode_handle is not None:
            return await self._prefill_handoff(payload, prompt_ids,
                                               params, model_id)
        _rid, queue = await self._submit(prompt_ids, params, model_id)
        if payload.get("stream"):
            return self._stream_from(queue)
        tokens: List[int] = []
        finish_reason = None
        while True:
            out = await queue.get()
            tokens.append(out.token)
            if out.finished:
                finish_reason = out.finish_reason
                break
        body = {"object": "text_completion",
                "choices": [{"token_ids": tokens,
                             "finish_reason": finish_reason}]}
        text = self._detok(tokens)
        if text is not None:
            body["choices"][0]["text"] = text
        return body

    async def _stream_from(self, queue: asyncio.Queue):
        while True:
            out = await queue.get()
            chunk = {"token": out.token, "finished": out.finished}
            if out.finished:
                chunk["finish_reason"] = out.finish_reason
            yield f"data: {json.dumps(chunk)}\n\n"
            if out.finished:
                return

    # --- disaggregated prefill/decode (fleet KV plane) ---

    async def _prefill_handoff(self, payload: Dict[str, Any],
                               prompt_ids: List[int],
                               params: SamplingParams,
                               model_id: Optional[str]):
        """Prefill-pool request path: run the prompt pass here, export
        the sequence's KV pages, ship them to a decode replica
        (chunked object-store puts) and proxy its reply back. A failed
        handoff retries against another decode replica; after the
        retry budget it raises an attributed error — never a hang."""
        import time

        loop = asyncio.get_event_loop()
        rid, q = await self._submit(prompt_ids, params, model_id)
        first = await q.get()
        self._queues.pop(rid, None)
        if first.finished:
            # done at its first token (stop token / max_tokens=1):
            # nothing to hand off; the pump already observed the state
            tokens = [first.token]
            if payload.get("stream"):
                chunk = {"token": first.token, "finished": True,
                         "finish_reason": first.finish_reason}

                async def _one():
                    yield f"data: {json.dumps(chunk)}\n\n"

                return _one()
            body = {"object": "text_completion",
                    "choices": [{"token_ids": tokens,
                                 "finish_reason": first.finish_reason}]}
            text = self._detok(tokens)
            if text is not None:
                body["choices"][0]["text"] = text
            return body

        t0 = time.perf_counter()

        def _export():
            with self._engine_lock:
                return self.engine.export_kv_request(rid)

        handoff = await loop.run_in_executor(None, _export)
        # export finished the request outside step(), so the pump never
        # emits its terminal output: observe + drop the state here
        state = self.engine.requests.pop(rid, None)
        if state is not None:
            self._observe_finished(state, time.perf_counter())
        k = handoff.pop("k")
        v = handoff.pop("v")
        nbytes = int(k.nbytes) + int(v.nbytes)

        from .. import put
        from .._private import failpoints
        from .._private.config import global_config

        # ship pages in serve_kv_handoff_chunk_bytes slices so one huge
        # context doesn't materialize as a single giant object
        chunk_bytes = max(1, int(global_config().serve_kv_handoff_chunk_bytes))
        n_pages = int(k.shape[1])
        per_page = max(1, (nbytes // max(1, n_pages)))
        pages_per_chunk = max(1, chunk_bytes // per_page)

        def _ship():
            refs = []
            for s in range(0, n_pages, pages_per_chunk):
                e = min(n_pages, s + pages_per_chunk)
                refs.append(put((k[:, s:e], v[:, s:e])))
            return refs

        refs = await loop.run_in_executor(None, _ship)
        decode_payload = {
            "handoff": handoff,
            "kv_refs": refs,
            "sampling": {"temperature": params.temperature,
                         "top_k": params.top_k, "top_p": params.top_p,
                         "max_tokens": params.max_tokens,
                         "stop_token_ids": list(params.stop_token_ids)},
            "stream": bool(payload.get("stream")),
        }
        last_err: Optional[BaseException] = None
        result = replica = None
        for _attempt in range(3):
            try:
                await failpoints.afire("serve.kv_handoff",
                                       detail=self._dep_name or "")
                from ..serve.replica import current_tenant_id

                tenant = current_tenant_id()
                ref, replica = await loop.run_in_executor(
                    None, lambda: self._decode_handle.route(
                        decode_payload, request_id=rid,
                        tenant_id=tenant))
                result = await ref
                break
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — retried, then attributed
                last_err = e
                if self._m_handoff_retries is not None:
                    self._m_handoff_retries.inc()
                # a dead decode replica is the expected failure: force a
                # replica-set refresh so the retry lands elsewhere
                await loop.run_in_executor(
                    None, lambda: self._decode_handle._refresh(force=True))
        else:
            raise RuntimeError(
                f"KV handoff for request {rid} failed after 3 attempts "
                f"against the decode pool of deployment "
                f"{self._dep_name!r}; last error: {last_err!r}")
        if self._m_handoff_bytes is not None:
            self._m_handoff_bytes.inc(nbytes)
            self._m_handoff_lat.observe(time.perf_counter() - t0)
        if isinstance(result, dict) and "__stream__" in result:
            return self._proxy_stream(replica, result["__stream__"])
        return result

    async def _proxy_stream(self, replica, stream_id: int):
        """Relay a decode replica's response stream chunk by chunk
        (same pull protocol the HTTP proxy uses)."""
        from ..serve.replica import _STREAM_END

        finished = False
        try:
            while True:
                chunk = await replica.next_chunk.remote(stream_id)
                if isinstance(chunk, str) and chunk == _STREAM_END:
                    finished = True
                    return
                yield chunk
        finally:
            if not finished:
                try:
                    await replica.cancel_stream.remote(stream_id)
                except Exception:  # graftlint: ignore[swallow] — the
                    # decode replica may already be dead; releasing its
                    # generator is best-effort and the client's stream
                    # already ended either way
                    pass

    async def decode_from_kv(self, payload: Dict[str, Any]):
        """Decode-pool entry: pull the shipped KV chunks, inject them
        into this engine (no prompt pass) and generate the remaining
        tokens. Unusable payloads fall back to recomputing the prefill
        locally inside the engine — slower, never wrong."""
        import time

        import numpy as np

        from .. import get
        from ..serve.replica import current_request_id

        loop = asyncio.get_event_loop()
        meta = dict(payload["handoff"])
        refs = list(payload.get("kv_refs") or ())
        if refs:
            parts = await loop.run_in_executor(
                None, lambda: get(refs, timeout=120))
            ks = [p[0] for p in parts]
            meta["k"] = ks[0] if len(ks) == 1 else np.concatenate(
                ks, axis=1)
            vs = [p[1] for p in parts]
            meta["v"] = vs[0] if len(vs) == 1 else np.concatenate(
                vs, axis=1)
        s = payload.get("sampling") or {}
        params = SamplingParams(
            temperature=float(s.get("temperature", 1.0)),
            top_k=int(s.get("top_k", 0)),
            top_p=float(s.get("top_p", 1.0)),
            max_tokens=int(s.get("max_tokens", 64)),
            stop_token_ids=tuple(s.get("stop_token_ids", ())))
        rid_in = current_request_id()
        if rid_in and (rid_in in self._queues
                       or rid_in in self.engine.requests):
            rid_in = None

        def _inject():
            with self._engine_lock:
                return self.engine.inject_request(meta, params,
                                                  request_id=rid_in)

        rid = await loop.run_in_executor(None, _inject)
        from ..serve.replica import current_tenant_id

        tenant = current_tenant_id()
        if tenant:
            self._tenants[rid] = tenant
        pre = [int(t) for t in meta.get("output") or ()]
        state = self.engine.requests.get(rid)
        if state is not None and state.finished:
            # degenerate: already at its token budget after prefill —
            # finished inside inject, so no pump output will ever come
            self.engine.requests.pop(rid, None)
            self._observe_finished(state, time.perf_counter())
            if payload.get("stream"):
                return self._stream_decode(pre, None,
                                           state.finish_reason)
            body = {"object": "text_completion",
                    "choices": [{"token_ids": pre,
                                 "finish_reason": state.finish_reason}]}
            text = self._detok(pre)
            if text is not None:
                body["choices"][0]["text"] = text
            return body
        q: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = q
        self._ensure_pump()
        if payload.get("stream"):
            return self._stream_decode(pre, q)
        tokens = list(pre)
        finish_reason = None
        while True:
            out = await q.get()
            tokens.append(out.token)
            if out.finished:
                finish_reason = out.finish_reason
                break
        body = {"object": "text_completion",
                "choices": [{"token_ids": tokens,
                             "finish_reason": finish_reason}]}
        text = self._detok(tokens)
        if text is not None:
            body["choices"][0]["text"] = text
        return body

    async def _stream_decode(self, pre: List[int],
                             queue: Optional[asyncio.Queue],
                             finish_reason: Optional[str] = None):
        """Stream a decode-pool response: replay the prefill-side
        tokens first (the client never saw them), then live decode."""
        for i, t in enumerate(pre):
            last = queue is None and i == len(pre) - 1
            chunk: Dict[str, Any] = {"token": t, "finished": last}
            if last:
                chunk["finish_reason"] = finish_reason
            yield f"data: {json.dumps(chunk)}\n\n"
        if queue is not None:
            async for chunk_str in self._stream_from(queue):
                yield chunk_str

    async def chat(self, payload: Dict[str, Any]):
        """Chat-completions shim: template the messages through the
        tokenizer (requires one) then run completions."""
        if self.tokenizer is None:
            raise ValueError("chat endpoint requires a tokenizer")
        msgs = payload["messages"]
        prompt_ids = self.tokenizer.apply_chat_template(
            msgs, add_generation_prompt=True)
        body = dict(payload)
        body.pop("messages")
        body["prompt_ids"] = prompt_ids
        return await self.completions(body)

    async def stats(self, _payload=None) -> Dict[str, Any]:
        out = self.engine.stats()
        out["pool"] = self._pool
        return out


def build_llm_deployment(model: str = "tiny", *, num_replicas: int = 1,
                         name: str = "llm",
                         pools: Optional[dict] = None, **server_kwargs):
    """An Application running LLMServer replicas (ref: ray.llm
    build_openai_app). ``pools={"prefill": n, "decode": m}`` deploys
    disaggregated prefill/decode pools instead of ``num_replicas``
    monolithic replicas (fleet KV plane)."""
    from .. import serve

    dep = serve.deployment(LLMServer, name=name,
                           num_replicas=num_replicas,
                           pools=pools)
    return dep.bind(model, **server_kwargs)
