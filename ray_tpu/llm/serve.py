"""LLM serving deployment: the engine behind an async serve replica.

Reference analog: ray.llm's serve deployments
(llm/_internal/serve/deployments/llm/llm_server.py wrapping vLLM's async
engine, + the OpenAI router in _internal/serve/deployments/routers/).
Here the continuous-batching engine runs on a replica-side thread; each
request registers an asyncio queue that the engine pump feeds, so many
HTTP streams multiplex over ONE decode batch — the continuous-batching
payoff serve exists to deliver.

Usage:
    app = build_llm_deployment("tiny", init="random")   # or params blob
    handle = serve.run(app)
    out = await handle.completions.remote({"prompt_ids": [...]})
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Dict, List, Optional

from ..models.llama import LLAMA_CONFIGS, LlamaConfig, init_params
from .engine import EngineConfig, LLMEngine
from .sampling import SamplingParams


class LLMServer:
    """Serve deployment class hosting one engine replica."""

    def __init__(self, model: str = "tiny", *, init: str = "random",
                 params_path: Optional[str] = None,
                 engine_config: Optional[dict] = None,
                 tokenizer: Optional[str] = None, seed: int = 0,
                 quantize: Optional[str] = None):
        import jax

        self.model_name = model
        if model in LLAMA_CONFIGS:
            cfg = LLAMA_CONFIGS[model]
        elif os.path.isdir(model):
            cfg = None  # an HF checkpoint directory IS the model source
        else:
            raise ValueError(f"unknown model {model!r}: not a named "
                             f"config or an HF checkpoint dir")
        if cfg is None or init == "hf":
            # real weights: HF safetensors directory (hf_interop.py) —
            # the vLLM-engine weight-loading analog
            from ..models.hf_interop import load_hf_checkpoint

            path = model if cfg is None else (params_path or model)
            if not os.path.isdir(path):
                raise ValueError(
                    f"init='hf' needs an HF checkpoint directory; "
                    f"{path!r} is not one (pass it as `model` or "
                    f"`params_path`)")
            # quantize="int8": host-side per-channel int8 before the
            # device sees anything — how Llama-3-8B serves on one 16 GB
            # chip (ops/quant.py)
            params, cfg = load_hf_checkpoint(path, quantize=quantize)
            params = jax.device_put(params)
            if tokenizer is None and os.path.exists(
                    os.path.join(path, "tokenizer_config.json")):
                tokenizer = path
        elif params_path:
            import pickle

            if quantize is not None:
                raise ValueError(
                    "quantize applies to HF-checkpoint loading only "
                    "(init='hf' / a checkpoint-dir model)")
            with open(params_path, "rb") as f:
                params = pickle.load(f)
            params = jax.device_put(params)
        elif init == "random":
            if quantize is not None:
                raise ValueError(
                    "quantize applies to HF-checkpoint loading only "
                    "(init='hf' / a checkpoint-dir model)")
            params = init_params(jax.random.PRNGKey(seed), cfg)
        else:
            raise ValueError(f"unknown init {init!r}")
        ecfg = EngineConfig(**(engine_config or {}))
        self.engine = LLMEngine(params, cfg, ecfg)
        self.tokenizer = None
        if tokenizer:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(tokenizer)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._pump_task: Optional[asyncio.Task] = None
        # serving metrics (ref: vLLM's engine stat logger — TTFT/TPOT
        # histograms, scheduler-state and cache-hit gauges), exported
        # through the util.metrics -> GCS -> /metrics pipeline
        from ..util import metrics

        tags = {"model": self.model_name}
        self._m_ttft = metrics.Histogram(
            "llm_ttft_seconds", "Time to first token per request",
            boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model",)).set_default_tags(tags)
        self._m_tpot = metrics.Histogram(
            "llm_tpot_seconds", "Time per output token (decode) "
            "per request", boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model",)).set_default_tags(tags)
        self._m_e2e = metrics.Histogram(
            "llm_request_e2e_seconds", "Arrival-to-finish request latency",
            boundaries=metrics.LATENCY_BUCKETS,
            tag_keys=("model",)).set_default_tags(tags)
        self._m_queue = metrics.Gauge(
            "llm_queue_depth", "Requests waiting for a decode slot",
            tag_keys=("model",)).set_default_tags(tags)
        self._m_occupancy = metrics.Gauge(
            "llm_batch_slot_occupancy",
            "Fraction of decode slots running (continuous batching)",
            tag_keys=("model",)).set_default_tags(tags)
        self._m_kv_util = metrics.Gauge(
            "llm_kv_page_utilization", "Fraction of KV-cache pages in use",
            tag_keys=("model",)).set_default_tags(tags)
        self._m_cache_hit = metrics.Counter(
            "llm_prefix_cache_hit_tokens_total",
            "Prompt tokens served from the prefix cache",
            tag_keys=("model",)).set_default_tags(tags)
        self._m_prompt = metrics.Counter(
            "llm_prompt_tokens_total", "Prompt tokens received",
            tag_keys=("model",)).set_default_tags(tags)
        self._m_generated = metrics.Counter(
            "llm_generation_tokens_total", "Tokens generated",
            tag_keys=("model",)).set_default_tags(tags)

    # --- engine pump: one thread-hop per step, fan-out to request queues ---

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_event_loop().create_task(
                self._pump())

    async def _pump(self) -> None:
        import time

        loop = asyncio.get_event_loop()
        while self.engine.has_unfinished():
            outs = await loop.run_in_executor(None, self.engine.step)
            for out in outs:
                q = self._queues.get(out.request_id)
                if q is not None:
                    q.put_nowait(out)
                if out.finished:
                    # the reader holds its queue reference; drop ours and
                    # the engine's state so a long-lived replica doesn't
                    # accumulate every past request
                    self._queues.pop(out.request_id, None)
                    state = self.engine.requests.pop(out.request_id, None)
                    if state is not None:
                        self._observe_finished(state,
                                               time.perf_counter())
            stats = self.engine.stats()
            self._m_queue.set(stats["waiting"])
            self._m_occupancy.set(
                stats["running"] / max(1, self.engine.ecfg.max_num_seqs))
            self._m_kv_util.set(
                1.0 - stats["free_pages"] / max(1, stats["total_pages"]))
            if not outs:
                await asyncio.sleep(0.002)

    def _observe_finished(self, state, now: float) -> None:
        """Fold one finished request into the latency histograms.
        Timestamps are engine-side perf_counter marks (RequestState
        arrival_t / first_token_t), so TTFT includes queueing."""
        tags = ({"model": state.model_id} if state.model_id else None)
        n_out = len(state.output)
        if state.first_token_t:
            self._m_ttft.observe(state.first_token_t - state.arrival_t,
                                 tags)
            if n_out > 1:
                self._m_tpot.observe(
                    (now - state.first_token_t) / (n_out - 1), tags)
        self._m_e2e.observe(now - state.arrival_t, tags)
        if state.cached_tokens:
            self._m_cache_hit.inc(state.cached_tokens, tags)
        self._m_prompt.inc(len(state.prompt), tags)
        if n_out:
            self._m_generated.inc(n_out, tags)

    async def _submit(self, prompt_ids: List[int],
                      params: SamplingParams,
                      model_id: Optional[str] = None) -> asyncio.Queue:
        from ..serve.replica import current_request_id

        rid_in = current_request_id()
        if rid_in and (rid_in in self._queues
                       or rid_in in self.engine.requests):
            rid_in = None  # client reused an id mid-flight: don't collide
        rid = self.engine.add_request(prompt_ids, params,
                                      request_id=rid_in,
                                      model_id=model_id)
        q: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = q
        self._ensure_pump()
        return q

    def _parse(self, payload: Dict[str, Any]):
        if "prompt_ids" in payload:
            prompt_ids = [int(t) for t in payload["prompt_ids"]]
        elif "prompt" in payload and self.tokenizer is not None:
            prompt_ids = self.tokenizer.encode(payload["prompt"])
        else:
            raise ValueError(
                "need 'prompt_ids' (or 'prompt' with a tokenizer configured)")
        params = SamplingParams(
            temperature=float(payload.get("temperature", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            max_tokens=int(payload.get("max_tokens", 64)),
            stop_token_ids=tuple(payload.get("stop_token_ids", ())))
        # OpenAI-style per-request "model": the server's base-model
        # name rides the base weights; any OTHER name must be a LOADED
        # LoRA adapter — an unknown name is a client error, not a
        # silent base-model fallback
        model_id = payload.get("model")
        if model_id is not None:
            if not isinstance(model_id, str):
                raise ValueError("'model' must be a string")
            if model_id in (self.model_name, "base", ""):
                model_id = None
            elif self.engine.lora_pool is None \
                    or model_id not in self.engine.lora_pool:
                loaded = (sorted(self.engine.lora_pool._slots)
                          if self.engine.lora_pool is not None else [])
                raise ValueError(
                    f"unknown model {model_id!r}: not this server's "
                    f"base model ({self.model_name!r}) or a loaded "
                    f"LoRA adapter ({loaded})")
        return prompt_ids, params, model_id

    def _detok(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        return self.tokenizer.decode(token_ids)

    # --- API methods (serve routes by method name; HTTP hits __call__) ---

    async def __call__(self, payload: Dict[str, Any]):
        """HTTP entry: chat if 'messages' present, else completions."""
        if isinstance(payload, dict) and "messages" in payload:
            return await self.chat(payload)
        return await self.completions(payload or {})

    async def completions(self, payload: Dict[str, Any]):
        """OpenAI-completions-shaped endpoint (ref: ray.llm's OpenAI
        router). ``stream=True`` returns an async generator serve turns
        into chunked HTTP (SSE-style ``data:`` lines)."""
        prompt_ids, params, model_id = self._parse(payload)
        queue = await self._submit(prompt_ids, params, model_id)
        if payload.get("stream"):
            return self._stream_from(queue)
        tokens: List[int] = []
        finish_reason = None
        while True:
            out = await queue.get()
            tokens.append(out.token)
            if out.finished:
                finish_reason = out.finish_reason
                break
        body = {"object": "text_completion",
                "choices": [{"token_ids": tokens,
                             "finish_reason": finish_reason}]}
        text = self._detok(tokens)
        if text is not None:
            body["choices"][0]["text"] = text
        return body

    async def _stream_from(self, queue: asyncio.Queue):
        while True:
            out = await queue.get()
            chunk = {"token": out.token, "finished": out.finished}
            if out.finished:
                chunk["finish_reason"] = out.finish_reason
            yield f"data: {json.dumps(chunk)}\n\n"
            if out.finished:
                return

    async def chat(self, payload: Dict[str, Any]):
        """Chat-completions shim: template the messages through the
        tokenizer (requires one) then run completions."""
        if self.tokenizer is None:
            raise ValueError("chat endpoint requires a tokenizer")
        msgs = payload["messages"]
        prompt_ids = self.tokenizer.apply_chat_template(
            msgs, add_generation_prompt=True)
        body = dict(payload)
        body.pop("messages")
        body["prompt_ids"] = prompt_ids
        return await self.completions(body)

    async def stats(self, _payload=None) -> Dict[str, Any]:
        return self.engine.stats()


def build_llm_deployment(model: str = "tiny", *, num_replicas: int = 1,
                         name: str = "llm", **server_kwargs):
    """An Application running LLMServer replicas (ref: ray.llm
    build_openai_app)."""
    from .. import serve

    dep = serve.deployment(LLMServer, name=name,
                           num_replicas=num_replicas)
    return dep.bind(model, **server_kwargs)
