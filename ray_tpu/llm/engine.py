"""LLMEngine: continuous batching over the paged-KV model runner.

Reference analog: the vLLM engine loop ray.llm wraps
(llm/_internal/serve/deployments/llm/vllm/vllm_engine.py) — request
queue -> schedule -> {prefill | decode} -> sample -> stream. Rebuilt
TPU-first:

  * decode batch has a FIXED width (``max_num_seqs`` slots) so one
    compiled decode executable serves the engine's whole lifetime —
    continuous batching = host-side slot assignment, not shape changes;
  * prefills are bucketed (power-of-2 padding) and run one request per
    step between decode steps (chunked-prefill-lite: bounded TTFT impact
    on running streams);
  * all paging is host-side (PageAllocator); the device never sees an
    allocation decision, only block tables.

The engine is synchronous and single-threaded by design — an actor wraps
it for serving (ray_tpu.llm.serve) the way vLLM's AsyncLLMEngine wraps
its LLMEngine.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig
from ..ops import rope_frequencies
from .cache import (KVCache, PageAllocator, PrefixCache, SequenceTable,
                    init_kv_cache)
from .runner import (decode_burst, prefill_bucket, prefill_sample,
                     verify_step)
from .sampling import SamplingParams


@dataclass
class EngineConfig:
    max_num_seqs: int = 8           # decode slots (static batch width)
    page_size: int = 16
    num_pages: int = 512            # incl. reserved dump page 0
    max_seq_len: int = 2048
    kv_dtype: Any = None            # default: model dtype
    # decode steps fused into one device dispatch (multi-step
    # scheduling); >1 amortizes host->device round trips at the cost of
    # up to burst-1 wasted tokens past a stop token
    decode_burst: int = 8
    # chunked prefill (vLLM --enable-chunked-prefill analog): process
    # prompts in chunks of this many tokens, interleaving decode bursts
    # between chunks so a long prompt doesn't stall running streams for
    # its whole prefill; also one compiled executable per (chunk, span)
    # instead of per pow-2 prompt bucket. Measured r3 on 1x v5e
    # (llama-400m, 3.5k prompt arriving into a live decode stream,
    # chunk=512): running stream's worst inter-token gap ~5800ms -> ~370ms
    # (novel-shape prefill compiles are the big spike chunking removes),
    # long prompt's own TTFT ~320ms -> ~1300ms. Chunked vs whole-prompt
    # logits agree to bf16 precision (argmax/top-5 identical; greedy
    # token streams may diverge after many steps, as between any two
    # correct bf16 attention implementations). 0 = whole-prompt.
    prefill_chunk: int = 0
    # finished RequestStates kept for inspection before FIFO eviction
    # (callers that stream from step() outputs never need them)
    finished_retention: int = 1024
    # multi-LoRA serving (vLLM --enable-lora analog, S-LoRA batched
    # adapters — llm/lora.py): rank > 0 builds a static adapter pool;
    # requests carry model_id and every slot in a decode batch can wear
    # a different adapter. Incompatible with prefix caching (cached
    # pages would mix adapters) and chunked prefill for now.
    lora_rank: int = 0
    max_loras: int = 8
    # speculative decoding (llm/spec_decode.py — Leviathan et al.): a
    # dict {"draft_config": ..., "num_draft_tokens": k} or SpecConfig.
    # Greedy requests get k draft tokens verified per round in one
    # batched forward; output stays token-identical to plain greedy
    # decode. None = off. Incompatible with lora_rank > 0.
    speculation: Any = None
    # automatic prefix caching (vLLM --enable-prefix-caching analog):
    # full prompt pages are content-addressed and SHARED across
    # sequences via page refcounts; a request whose prompt prefix is
    # cached skips that prefix's prefill compute entirely (chunked
    # prefill starts past it). Forces chunked-prefill mode.
    enable_prefix_caching: bool = False


@dataclass
class RequestState:
    request_id: str
    prompt: List[int]
    params: SamplingParams
    output: List[int] = field(default_factory=list)
    slot: int = -1
    ctx_len: int = 0          # 0 until prefill completes
    prefill_pos: int = 0      # chunked prefill progress (tokens written)
    prompt_page_keys: Any = None   # prefix-cache keys (full pages)
    cached_tokens: int = 0         # prefix tokens served from the cache
    model_id: Optional[str] = None # LoRA adapter name (None = base)
    finished: bool = False
    finish_reason: Optional[str] = None
    arrival_t: float = 0.0
    first_token_t: float = 0.0


@dataclass
class StepOutput:
    request_id: str
    token: int
    finished: bool
    finish_reason: Optional[str] = None
    text_offset: int = 0


class LLMEngine:
    def __init__(self, params, cfg: LlamaConfig,
                 engine_config: Optional[EngineConfig] = None):
        self.cfg = cfg
        from .._private.config import global_config

        # resolved once per engine: a static jit arg, so the flag is
        # part of every decode executable's cache key
        self._paged_kernel = bool(global_config().llm_paged_kernel)
        # auto-select threshold (pages): long-context rounds stream
        # pages through the Pallas kernel even when the flag is off
        self._paged_min_pages = int(
            getattr(global_config(), "llm_paged_kernel_min_ctx_pages", 0))
        self.ecfg = engine_config or EngineConfig()
        if self.ecfg.max_seq_len > cfg.max_seq:
            raise ValueError("engine max_seq_len exceeds model max_seq")
        usable = self.ecfg.num_pages - 1  # page 0 is the dump page
        need = -(-self.ecfg.max_seq_len // self.ecfg.page_size)
        if need > usable:
            # guarantees a lone running sequence can always grow to
            # max_seq_len, which keeps preemption deadlock-free
            raise ValueError(
                f"num_pages={self.ecfg.num_pages} cannot hold one "
                f"max_seq_len={self.ecfg.max_seq_len} sequence "
                f"({need} pages needed, {usable} usable)")
        self.params = params
        self.cache = init_kv_cache(cfg, self.ecfg.num_pages,
                                   self.ecfg.page_size,
                                   self.ecfg.kv_dtype)
        self.allocator = PageAllocator(self.ecfg.num_pages,
                                       self.ecfg.page_size)
        self.lora_pool = None
        if self.ecfg.lora_rank > 0:
            from .lora import LoRAPool

            if self.ecfg.enable_prefix_caching:
                raise ValueError(
                    "lora_rank and enable_prefix_caching are mutually "
                    "exclusive (cached pages would mix adapters)")
            if self.ecfg.prefill_chunk > 0:
                raise ValueError(
                    "lora_rank requires whole-prompt prefill "
                    "(prefill_chunk=0) for now")
            self.lora_pool = LoRAPool(cfg, self.ecfg.lora_rank,
                                      self.ecfg.max_loras,
                                      dtype=cfg.dtype)
        self.prefix_cache: Optional[PrefixCache] = None
        if self.ecfg.enable_prefix_caching:
            self.prefix_cache = PrefixCache(self.allocator)
            if self.ecfg.prefill_chunk <= 0:
                # cached-prefix requests resume mid-prompt, which is the
                # chunked runner's contract. COPY before adjusting — the
                # caller's config object must not mutate under it.
                import dataclasses as _dc

                self.ecfg = _dc.replace(
                    self.ecfg,
                    prefill_chunk=min(512, self.ecfg.max_seq_len))
        max_pages = self.allocator.pages_needed(self.ecfg.max_seq_len)
        self.seq_table = SequenceTable(self.ecfg.max_num_seqs, max_pages)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq,
                                    cfg.rope_theta)
        self.cos, self.sin = jax.device_put(cos), jax.device_put(sin)
        # speculative decoding (drafter + verify window; spec_decode.py)
        self.spec = None
        # fleet verify hook (llm/serve.py): (payload, draft) ->
        # Optional[List[int]] — ships a KV snapshot to a prefill-class
        # verifier racing the local verify; None/exception = local only
        self._spec_remote_verify = None
        if self.ecfg.speculation:
            self.enable_speculation(self.ecfg.speculation)
        self.waiting: Deque[RequestState] = collections.deque()
        # admitted (slot+pages held) but not yet fully prefilled; one
        # prefill work unit runs per step — a whole prompt, or one chunk
        self._prefill_queue: Deque[RequestState] = collections.deque()
        self._prefill_skips: Dict[str, int] = {}  # SRF aging counters
        self.slots: List[Optional[RequestState]] = (
            [None] * self.ecfg.max_num_seqs)
        self.requests: Dict[str, RequestState] = {}
        self._finished_order: Deque[str] = collections.deque()
        self._seed = 0
        self._id = itertools.count()
        # device-side block-table cache, refreshed only when the host
        # table mutates (saves one H2D upload per decode step)
        self._bt_device = None
        self._bt_version = -1

    # --- public API ---

    def add_request(self, prompt_tokens: List[int],
                    params: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None,
                    model_id: Optional[str] = None) -> str:
        if not prompt_tokens:
            raise ValueError("empty prompt")
        if model_id is not None:
            if self.lora_pool is None:
                raise ValueError("model_id requires EngineConfig."
                                 "lora_rank > 0")
            self.lora_pool.slot_of(model_id)   # validate at submission
        if len(prompt_tokens) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt_tokens)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}")
        rid = request_id or f"req-{next(self._id)}"
        state = RequestState(rid, list(prompt_tokens),
                             params or SamplingParams(),
                             arrival_t=time.perf_counter(),
                             model_id=model_id)
        self.waiting.append(state)
        self.requests[rid] = state
        return rid

    def enable_speculation(self, spec, draft_params=None) -> None:
        """Attach a drafter (spec_decode.SpecDecoder). ``spec`` is the
        ``speculation`` dict/SpecConfig; ``draft_params`` overrides the
        drafter's random init (a trained 400m draft checkpoint)."""
        from .spec_decode import SpecDecoder

        if self.lora_pool is not None:
            raise ValueError("speculation is incompatible with "
                             "lora_rank > 0 (drafter has no adapters)")
        self.spec = SpecDecoder(self.cfg, self.ecfg, spec,
                                draft_params=draft_params)

    def disable_speculation(self) -> None:
        self.spec = None

    def abort_request(self, request_id: str) -> None:
        state = self.requests.get(request_id)
        if state is None or state.finished:
            return
        self._finish(state, "aborted")

    def has_unfinished(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def step(self, skip_decode: bool = False) -> List[StepOutput]:
        """One scheduling round: admit waiting requests into free slots
        (host-side bookkeeping only), advance ONE prefill work unit (a
        whole prompt, or one chunk of one prompt), then one batched
        decode burst for every decoding slot. ``skip_decode`` runs only
        the admission/prefill phase (TTFT measurement, draining a
        prefill backlog before decoding)."""
        outputs: List[StepOutput] = []
        # purge stale entries (aborted/preempted mid-queue) FIRST: they
        # must neither count toward the admission cap nor linger
        if any(s.slot < 0 or s.finished for s in self._prefill_queue):
            self._prefill_queue = collections.deque(
                s for s in self._prefill_queue
                if s.slot >= 0 and not s.finished)
        # admission never blocks on prefill, but the queue is capped:
        # admission reserves the WHOLE sequence's pages, so admitting
        # every waiting request up front would pin pages that running
        # streams need (recompute-preemption cost). Whole-prompt mode
        # caps at 1 — exactly the old admit-and-prefill-per-step pace.
        cap = 1 if self.ecfg.prefill_chunk <= 0 else 2
        while len(self._prefill_queue) < cap:
            admitted = self._admit()
            if admitted is None:
                break
            self._prefill_queue.append(admitted)
        pref = self._next_prefill()
        if pref is not None:
            outputs.extend(self._run_prefill(pref))
            if pref.ctx_len > 0 or pref.slot < 0 or pref.finished:
                # done (or preempted/aborted meanwhile): leave the queue
                try:
                    self._prefill_queue.remove(pref)
                except ValueError:
                    pass
        if not skip_decode and any(
                s is not None and s.ctx_len > 0 for s in self.slots):
            outputs.extend(self._run_decode())
        return outputs

    # consecutive work units a queued prefill may be passed over before
    # it runs regardless of length (anti-starvation aging for SRF)
    _PREFILL_MAX_SKIPS = 8

    def _next_prefill(self) -> Optional[RequestState]:
        """Pick this round's prefill work unit. Whole-prompt mode keeps
        FIFO order. Chunked mode picks the request with the FEWEST
        remaining prefill tokens (arrival-order tiebreak) — a short
        prompt admitted behind a long one starts streaming after its own
        chunk count — with aging: the oldest queued request runs after
        at most _PREFILL_MAX_SKIPS pass-overs, so a sustained stream of
        short prompts cannot starve a long one indefinitely."""
        while self._prefill_queue and (
                self._prefill_queue[0].slot < 0
                or self._prefill_queue[0].finished):
            self._prefill_queue.popleft()  # preempted/aborted
        live = [s for s in self._prefill_queue
                if s.slot >= 0 and not s.finished]
        # aging counters live exactly as long as their queue entry
        # (aborted/preempted requests must not leak entries)
        live_ids = {s.request_id for s in live}
        for rid in [r for r in self._prefill_skips if r not in live_ids]:
            del self._prefill_skips[rid]
        if not live:
            return None
        if self.ecfg.prefill_chunk <= 0:
            return live[0]
        oldest = min(live, key=lambda s: s.arrival_t)
        if self._prefill_skips.get(oldest.request_id, 0) \
                >= self._PREFILL_MAX_SKIPS:
            pick = oldest
        else:
            pick = min(live, key=lambda s: (
                len(s.prompt) + len(s.output) - s.prefill_pos,
                s.arrival_t))
        for s in live:
            if s is pick:
                self._prefill_skips.pop(s.request_id, None)
            else:
                self._prefill_skips[s.request_id] = (
                    self._prefill_skips.get(s.request_id, 0) + 1)
        return pick

    def generate(self, prompts: List[List[int]],
                 params: Optional[SamplingParams] = None) -> List[List[int]]:
        """Batch entry point: run all prompts to completion. Outputs are
        collected from step() results, so batches larger than the
        finished-request retention window work fine."""
        ids = [self.add_request(p, params) for p in prompts]
        collected: Dict[str, List[int]] = {rid: [] for rid in ids}
        while self.has_unfinished():
            for out in self.step():
                if out.request_id in collected:
                    collected[out.request_id].append(out.token)
        return [collected[rid] for rid in ids]

    # --- scheduling internals ---

    def _free_slot(self) -> int:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return -1

    def _admit(self) -> Optional[RequestState]:
        if not self.waiting:
            return None
        slot = self._free_slot()
        if slot < 0:
            return None
        state = self.waiting[0]
        # pages for the whole sequence so far (prompt + any tokens
        # generated before a preemption) + the next generated token
        seq_len = len(state.prompt) + len(state.output)
        cached_pages: List[int] = []
        if self.prefix_cache is not None:
            if state.prompt_page_keys is None:
                state.prompt_page_keys = PrefixCache.page_keys(
                    state.prompt, self.ecfg.page_size)
            hits = self.prefix_cache.lookup(state.prompt_page_keys)
            # at least one prompt token must run through prefill (its
            # logits seed sampling): never cache the WHOLE prompt
            cap = (len(state.prompt) - 1) // self.ecfg.page_size
            while len(hits) > cap:
                self.allocator.free([hits.pop()])
            cached_pages = hits
        fresh_tokens = seq_len + 1 - len(cached_pages) * self.ecfg.page_size
        if not self.allocator.can_allocate(fresh_tokens):
            if self.prefix_cache is not None:
                need = self.allocator.pages_needed(fresh_tokens)
                # only sacrifice cached prefixes when eviction can
                # actually enable THIS admission
                if (self.allocator.free_pages
                        + self.prefix_cache.evictable()) >= need:
                    self.prefix_cache.evict_for(fresh_tokens)
            if not self.allocator.can_allocate(fresh_tokens):
                if cached_pages:
                    self.allocator.free(cached_pages)
                return None
        self.waiting.popleft()
        pages = cached_pages + self.allocator.allocate(
            self.allocator.pages_needed(fresh_tokens))
        state.slot = slot
        state.cached_tokens = len(cached_pages) * self.ecfg.page_size
        state.prefill_pos = state.cached_tokens
        self.slots[slot] = state
        self.seq_table.assign(slot, pages)
        return state

    # block-table span bucket width, in pages: bounds compiled decode
    # variants to max_pages/span while letting KV reads scale with the
    # longest ACTIVE context instead of max_seq_len
    _SPAN_PAGES = 4

    def _bt(self, span: Optional[int] = None):
        key = (self.seq_table.version, span)
        if self._bt_version != key:
            table = self.seq_table.block_tables
            if span is not None:
                table = table[:, :span]
            self._bt_device = jnp.asarray(table)
            self._bt_version = key
        return self._bt_device

    def _span_bucket(self, pages: int) -> int:
        """Power-of-2 page-span bucket, capped at the table width."""
        b = self._SPAN_PAGES
        while b < pages:
            b *= 2
        return min(b, self.seq_table.block_tables.shape[1])

    def _active_span(self) -> int:
        """Pages covering the longest DECODING sequence, bucketed.
        Mid-prefill slots (ctx_len 0) hold their full page allocation up
        front — counting them would balloon every interleaved decode
        burst's KV gather to the long prompt's whole table."""
        longest = max((int(self.seq_table.n_pages[s.slot])
                       for s in self.slots
                       if s is not None and s.ctx_len > 0), default=1)
        return self._span_bucket(longest)

    def _sampling_arrays(self, row_states, advance: int = 1):
        n = len(row_states)
        temp = np.ones(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.ones(n, np.float32)
        # all-greedy rounds compile the argmax-only epilogue (runner
        # prefill_sample/decode_burst `greedy`): identical outputs,
        # simpler program (inactive slots count as greedy)
        greedy = True
        for i, s in enumerate(row_states):
            if s is None:
                continue
            temp[i] = s.params.temperature
            top_k[i] = s.params.top_k
            top_p[i] = s.params.top_p
            if s.params.temperature > 0.0:
                greedy = False
        seed = self._seed
        self._seed += advance  # burst step i uses seed+i: no reuse
        return (seed, jnp.asarray(temp), jnp.asarray(top_k),
                jnp.asarray(top_p), greedy)

    def _run_prefill(self, state: RequestState) -> List[StepOutput]:
        """Prefill the sequence so far (prompt, plus prior output when
        resuming after preemption — vLLM's recompute-preemption) and
        sample the next token. Whole-prompt mode fuses everything in one
        dispatch; chunked mode advances ONE chunk and only samples after
        the final chunk."""
        seq = state.prompt + state.output
        L = len(seq)
        C = self.ecfg.prefill_chunk
        if C > 0:
            return self._run_prefill_chunk(state, seq, L, C)
        bucket = prefill_bucket(L, self.ecfg.max_seq_len)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :L] = seq
        seed, temp, top_k, top_p, greedy = self._sampling_arrays([state])
        lora = None
        if self.lora_pool is not None:
            lora = self.lora_pool.select(
                [self.lora_pool.slot_of(state.model_id)])
        toks, ck, cv = prefill_sample(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray([L], jnp.int32),
            jnp.asarray(self.seq_table.block_tables[
                state.slot:state.slot + 1]),
            self.cos, self.sin, seed, temp, top_k, top_p, lora,
            cfg=self.cfg, greedy=greedy)
        self.cache = KVCache(ck, cv)
        state.ctx_len = L
        tok = int(np.asarray(toks)[0])
        if not state.output:
            state.first_token_t = time.perf_counter()
        return [self._append_token(state, tok)]

    def _run_prefill_chunk(self, state: RequestState, seq: List[int],
                           L: int, C: int) -> List[StepOutput]:
        from .runner import prefill_chunk, sample_logits

        start = state.prefill_pos
        n = min(C, L - start)
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :n] = seq[start:start + n]
        # table span bucketed over the pages this chunk can touch, so a
        # handful of executables serve every prompt length
        span = self._span_bucket(-(-(start + n) // self.ecfg.page_size))
        bt = jnp.asarray(
            self.seq_table.block_tables[state.slot:state.slot + 1, :span])
        logits, ck, cv = prefill_chunk(
            self.params, self.cache.k, self.cache.v, jnp.asarray(tokens),
            jnp.int32(start), jnp.int32(n), bt, self.cos, self.sin,
            cfg=self.cfg)
        self.cache = KVCache(ck, cv)
        state.prefill_pos = start + n
        if state.prefill_pos < L:
            return []  # more chunks to go; decode interleaves meanwhile
        if self.prefix_cache is not None and state.prompt_page_keys:
            # prompt pages are now fully written: publish them for
            # future requests sharing the prefix
            table = self.seq_table.block_tables[state.slot]
            self.prefix_cache.insert(
                state.prompt_page_keys,
                [int(p) for p in table[:len(state.prompt_page_keys)]])
        seed, temp, top_k, top_p, _greedy = self._sampling_arrays([state])
        tok = int(np.asarray(sample_logits(
            logits, seed, temp, top_k, top_p))[0])
        state.ctx_len = L
        if not state.output:
            state.first_token_t = time.perf_counter()
        return [self._append_token(state, tok)]

    def _preempt(self, state: RequestState) -> None:
        """Recompute-preemption (vLLM style): release the sequence's
        pages and put it back at the head of the waiting queue; its
        generated-so-far tokens re-prefill on readmission."""
        if self.spec is not None:
            self.spec.drop(state.slot)   # drafter KV dies with the pages
        self.allocator.free(self.seq_table.pages_of(state.slot))
        self.seq_table.clear(state.slot)
        self.slots[state.slot] = None
        state.slot = -1
        state.ctx_len = 0
        state.prefill_pos = 0  # chunked progress restarts with the pages
        state.cached_tokens = 0
        try:
            self._prefill_queue.remove(state)
        except ValueError:
            pass
        self.waiting.appendleft(state)

    def _pick_victim(self, exclude: RequestState) -> Optional[RequestState]:
        candidates = [s for s in self.slots
                      if s is not None and s is not exclude]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.arrival_t)  # youngest

    def _burst_width(self) -> int:
        """Fused steps this round: capped by every active slot's headroom
        to max_seq_len and by its remaining token budget (don't burn a
        full burst when everyone needs one more token). Mid-prefill
        slots (ctx_len 0) don't decode and don't cap the burst."""
        K = self.ecfg.decode_burst
        for s in self.slots:
            if s is None or s.ctx_len == 0:
                continue
            K = min(K, self.ecfg.max_seq_len - 1 - s.ctx_len + 1,
                    s.params.max_tokens - len(s.output))
        return max(1, K)

    def _provision_pages(self, s: RequestState, upto: int) -> None:
        """Ensure slot pages cover positions [0, upto); preempt youngest
        others when the pool runs dry (init guarantees a lone sequence
        always fits)."""
        while int(self.seq_table.n_pages[s.slot]) * self.ecfg.page_size \
                < upto:
            if self.allocator.free_pages < 1 and self.prefix_cache:
                self.prefix_cache.evict(1)   # cache before victims
            if self.allocator.free_pages >= 1:
                self.seq_table.append_page(
                    s.slot, self.allocator.allocate(1)[0])
                continue
            victim = self._pick_victim(exclude=s)
            if victim is None:
                raise MemoryError(
                    "single sequence exhausted the KV cache — "
                    "num_pages/max_seq_len misconfigured")
            self._preempt(victim)

    def _run_decode(self) -> List[StepOutput]:
        if self.spec is not None:
            outs = self._run_spec_decode()
            if outs is not None:
                return outs
        B = self.ecfg.max_num_seqs
        K = self._burst_width()
        for s in [s for s in self.slots
                  if s is not None and s.ctx_len > 0]:
            if s.slot < 0:
                continue  # preempted as a victim earlier this round
            self._provision_pages(s, s.ctx_len + K)
        # mid-prefill slots (chunked) hold pages but don't decode yet
        active_states = [s for s in self.slots
                         if s is not None and s.ctx_len > 0]
        if not active_states:
            return []
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        active = np.zeros(B, bool)
        for s in active_states:
            last = s.output[-1] if s.output else s.prompt[-1]
            tokens[s.slot] = last
            positions[s.slot] = s.ctx_len
            active[s.slot] = True
        seed, temp, top_k, top_p, greedy = self._sampling_arrays(
            self.slots, advance=K)
        lora = None
        if self.lora_pool is not None:
            ids = [0] * self.ecfg.max_num_seqs
            for s2 in active_states:
                ids[s2.slot] = self.lora_pool.slot_of(s2.model_id)
            lora = self.lora_pool.select(ids)
        span = self._active_span()
        use_paged = self._paged_kernel or (
            self._paged_min_pages > 0 and span >= self._paged_min_pages)
        toks, ck, cv = decode_burst(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(positions),
            self._bt(span),
            jnp.asarray(active), self.cos, self.sin,
            seed, temp, top_k, top_p, lora, cfg=self.cfg, n_steps=K,
            paged_kernel=use_paged, greedy=greedy)
        self.cache = KVCache(ck, cv)
        sampled = np.asarray(toks)  # [K, B]
        outs = []
        for s in active_states:
            for k in range(K):
                s.ctx_len += 1
                outs.append(self._append_token(s, int(sampled[k, s.slot])))
                if s.finished:
                    break
        return outs

    # --- speculative decoding (spec_decode.py; Leviathan et al.) ---

    def _spec_eligible(self, s: RequestState) -> bool:
        """Greedy-only speculation: accept-prefix semantics reproduce
        the greedy oracle exactly. Sampled/LoRA requests coexist in the
        same verify window (position 0 only) unsped."""
        return s.params.temperature == 0.0 and s.model_id is None

    def _run_spec_decode(self) -> Optional[List[StepOutput]]:
        """One draft+verify round over the whole slot batch: the drafter
        proposes k tokens per eligible slot, verify_step scores every
        slot's window in ONE dispatch (non-drafted slots are a 1-token
        window — they advance one token, like a plain decode step), and
        accept-prefix emits 1..k+1 tokens per drafted slot. Returns None
        when no slot can draft this round (caller falls back to the
        plain decode burst)."""
        from .spec_decode import accept_prefix

        spec = self.spec
        kd = spec.k

        def can_draft(s: RequestState) -> bool:
            # the window [p .. p+k] must fit under max_seq_len, and a
            # request one token from its budget gains nothing
            return (self._spec_eligible(s)
                    and s.ctx_len + kd <= self.ecfg.max_seq_len - 1
                    and s.params.max_tokens - len(s.output) >= 2)

        if not any(s is not None and s.ctx_len > 0 and can_draft(s)
                   for s in self.slots):
            return None
        # provision BEFORE array assembly — may preempt victims, so
        # drafted/active sets are derived again afterwards
        for s in [s for s in self.slots
                  if s is not None and s.ctx_len > 0]:
            if s.slot < 0:
                continue  # preempted as a victim earlier this round
            upto = s.ctx_len + (kd + 1 if can_draft(s) else 1)
            self._provision_pages(s, upto)
        active_states = [s for s in self.slots
                         if s is not None and s.ctx_len > 0]
        if not active_states:
            return []
        drafted_states = [s for s in active_states if can_draft(s)]
        if not drafted_states:
            return None
        # lazy drafter warm-up: first drafted round for a slot (or the
        # first after a drop) prefills the draft KV for its sequence
        for s in drafted_states:
            if s.slot not in spec.ready:
                seq = s.prompt + s.output
                spec.prefill(seq[:s.ctx_len],
                             self.seq_table.block_tables[
                                 s.slot:s.slot + 1])
                spec.ready.add(s.slot)
        span = self._active_span()
        bt = self._bt(span)
        items = []
        for s in drafted_states:
            seq = s.prompt + s.output
            p = s.ctx_len
            items.append((s.slot, seq[p - 1], seq[p], p))
        drafts = spec.draft(items, bt)
        # fleet mode: ship (KV snapshot, draft) to a prefill-class
        # verifier racing the local verify below; by the greedy-
        # continuation equivalence both compute the same emission, so
        # the remote result is corroboration + placement, never truth
        remote: Dict[int, List[int]] = {}
        if self._spec_remote_verify is not None:
            for s in drafted_states:
                try:
                    payload = self.snapshot_kv_request(s.request_id)
                    res = self._spec_remote_verify(payload,
                                                   drafts[s.slot])
                except Exception:
                    res = None
                if res is not None:
                    remote[s.slot] = [int(t) for t in res]
        B = self.ecfg.max_num_seqs
        S = kd + 1
        tok = np.zeros((B, S), np.int32)
        pos = np.full((B, S), -1, np.int32)
        for s in active_states:
            tok[s.slot, 0] = s.output[-1] if s.output else s.prompt[-1]
            pos[s.slot, 0] = s.ctx_len
            d = drafts.get(s.slot)
            if d:
                tok[s.slot, 1:1 + len(d)] = d
                pos[s.slot, 1:1 + len(d)] = (
                    s.ctx_len + 1 + np.arange(len(d)))
        seed, temp, top_k, top_p, greedy = self._sampling_arrays(
            self.slots, advance=1)
        t0 = time.perf_counter()
        tgt, samp0, ck, cv = verify_step(
            self.params, self.cache.k, self.cache.v, jnp.asarray(tok),
            jnp.asarray(pos), bt, self.cos, self.sin, seed, temp,
            top_k, top_p, cfg=self.cfg, greedy=greedy)
        self.cache = KVCache(ck, cv)
        tgt = np.asarray(tgt)
        samp0 = np.asarray(samp0)
        spec.verify_times.append(time.perf_counter() - t0)
        outs: List[StepOutput] = []
        for s in active_states:
            if s.slot < 0 or s.finished:
                continue
            d = drafts.get(s.slot)
            if d:
                emitted = accept_prefix(d, tgt[s.slot].tolist())
                spec.on_round(len(d), len(emitted) - 1)
                r = remote.get(s.slot)
                if r is not None:
                    spec.remote_rounds_total += 1
                    if r == emitted:
                        spec.remote_agree_total += 1
            else:
                emitted = [int(samp0[s.slot])]
            for t in emitted:
                s.ctx_len += 1
                outs.append(self._append_token(s, t))
                if s.finished:
                    break
        return outs

    def verify_request(self, request_id: str,
                       draft: List[int]) -> List[int]:
        """Run ONE verification round for a single request against an
        externally-supplied draft (the fleet verifier: the draft came
        from a decode-class replica's drafter, the KV arrived via
        inject_request). Applies and returns the emission — identical
        to the monolithic round by accept-prefix semantics. An empty
        draft degenerates to one plain greedy step. Greedy-only; other
        slots in the batch are untouched."""
        from .spec_decode import accept_prefix

        state = self.requests.get(request_id)
        if state is None:
            raise ValueError(f"unknown request {request_id!r}")
        if state.finished or state.slot < 0 or state.ctx_len <= 0:
            raise ValueError(
                f"request {request_id!r} is not verifiable "
                f"(finished={state.finished}, ctx_len={state.ctx_len})")
        if state.params.temperature != 0.0:
            raise ValueError("speculative verification is greedy-only")
        if state.model_id is not None:
            raise ValueError("speculative verification does not "
                             "support LoRA requests")
        draft = [int(t) for t in draft]
        # clamp the window to the sequence budget (mirrors the
        # monolithic round's eligibility rule near max_seq_len)
        while draft and (state.ctx_len + len(draft)
                         > self.ecfg.max_seq_len - 1):
            draft.pop()
        kd = len(draft)
        self._provision_pages(state, state.ctx_len + kd + 1)
        B = self.ecfg.max_num_seqs
        tok = np.zeros((B, kd + 1), np.int32)
        pos = np.full((B, kd + 1), -1, np.int32)
        seq = state.prompt + state.output
        tok[state.slot, 0] = seq[-1]
        pos[state.slot, 0] = state.ctx_len
        if kd:
            tok[state.slot, 1:] = draft
            pos[state.slot, 1:] = state.ctx_len + 1 + np.arange(kd)
        seed, temp, top_k, top_p, _g = self._sampling_arrays(
            self.slots, advance=1)
        span = self._span_bucket(int(self.seq_table.n_pages[state.slot]))
        t0 = time.perf_counter()
        tgt, _s0, ck, cv = verify_step(
            self.params, self.cache.k, self.cache.v, jnp.asarray(tok),
            jnp.asarray(pos), self._bt(span), self.cos, self.sin,
            seed, temp, top_k, top_p, cfg=self.cfg, greedy=True)
        self.cache = KVCache(ck, cv)
        row = np.asarray(tgt)[state.slot].tolist()
        if self.spec is not None:
            self.spec.verify_times.append(time.perf_counter() - t0)
        emitted = accept_prefix(draft, row)
        if self.spec is not None and kd:
            self.spec.on_round(kd, len(emitted) - 1)
        for t in emitted:
            state.ctx_len += 1
            self._append_token(state, t)
            if state.finished:
                break
        return emitted

    def _append_token(self, state: RequestState, token: int) -> StepOutput:
        state.output.append(token)
        reason = None
        if token in state.params.stop_token_ids:
            reason = "stop"
        elif len(state.output) >= state.params.max_tokens:
            reason = "length"
        elif state.ctx_len + 1 >= self.ecfg.max_seq_len:
            reason = "length"
        if reason:
            self._finish(state, reason)
        return StepOutput(state.request_id, token, state.finished,
                          state.finish_reason,
                          text_offset=len(state.output) - 1)

    def _finish(self, state: RequestState, reason: str) -> None:
        state.finished = True
        state.finish_reason = reason
        if state.slot >= 0:
            if self.spec is not None:
                self.spec.drop(state.slot)
            self.allocator.free(self.seq_table.pages_of(state.slot))
            self.seq_table.clear(state.slot)
            self.slots[state.slot] = None
            state.slot = -1
        elif state in self.waiting:
            self.waiting.remove(state)
        # bounded retention: a long-lived serving engine must not keep
        # every finished request's token lists forever
        self._finished_order.append(state.request_id)
        while len(self._finished_order) > self.ecfg.finished_retention:
            old = self._finished_order.popleft()
            stale = self.requests.get(old)
            if stale is not None and stale.finished:
                del self.requests[old]

    # --- fleet KV plane: prefill->decode handoff ---

    def export_kv_request(self, request_id: str) -> Dict[str, Any]:
        """Export a prefilled request's KV pages for decode on ANOTHER
        engine (disaggregated prefill/decode serving — DistServe/
        Splitwise lineage; llm/serve.py pools). Valid once the request
        has prefilled (ctx_len > 0), typically right after its first
        sampled token. Copies the sequence's pages to host memory,
        finishes the request locally (reason "handoff" — its slot and
        pages free immediately for the next prompt) and returns a
        payload :meth:`inject_request` accepts on the decode engine."""
        payload = self.snapshot_kv_request(request_id)
        self._finish(self.requests[request_id], "handoff")
        return payload

    def snapshot_kv_request(self, request_id: str) -> Dict[str, Any]:
        """Non-destructive :meth:`export_kv_request`: same payload, but
        the request keeps running HERE. The fleet spec-verify path ships
        snapshots to a prefill-class verifier while local decode
        continues — both compute the identical emission (spec_decode.py
        module docstring), so nothing is handed off."""
        state = self.requests.get(request_id)
        if state is None:
            raise ValueError(f"unknown request {request_id!r}")
        if state.finished or state.slot < 0 or state.ctx_len <= 0:
            raise ValueError(
                f"request {request_id!r} is not exportable "
                f"(finished={state.finished}, ctx_len={state.ctx_len})")
        n_kv = self.allocator.pages_needed(state.ctx_len)
        pages = self.seq_table.pages_of(state.slot)[:n_kv]
        idx = jnp.asarray(pages, jnp.int32)
        return {
            "prompt": list(state.prompt),
            "output": list(state.output),
            "ctx_len": state.ctx_len,
            "page_size": self.ecfg.page_size,
            "model_id": state.model_id,
            "k": np.asarray(self.cache.k[:, idx]),
            "v": np.asarray(self.cache.v[:, idx]),
        }

    def inject_request(self, payload: Dict[str, Any],
                       params: Optional[SamplingParams] = None,
                       request_id: Optional[str] = None) -> str:
        """Admit a request whose prompt pass ran on ANOTHER engine (the
        decode half of disaggregated serving). The shipped pages land in
        free cache pages and the request joins decode directly — no
        prefill compute here. When they CAN'T land (no free slot,
        page-size mismatch, pool pressure, malformed/missing arrays)
        the request joins the waiting queue and recomputes its prefill
        locally (recompute-preemption semantics): slower, never wrong."""
        prompt = [int(t) for t in payload["prompt"]]
        output = [int(t) for t in payload.get("output") or ()]
        ctx_len = int(payload["ctx_len"])
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) >= self.ecfg.max_seq_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_seq_len "
                f"{self.ecfg.max_seq_len}")
        model_id = payload.get("model_id")
        if model_id is not None:
            if self.lora_pool is None:
                raise ValueError("model_id requires EngineConfig."
                                 "lora_rank > 0")
            self.lora_pool.slot_of(model_id)
        rid = request_id or f"req-{next(self._id)}"
        if rid in self.requests:
            rid = f"req-{next(self._id)}"
        state = RequestState(rid, prompt, params or SamplingParams(),
                             output=output,
                             arrival_t=time.perf_counter(),
                             model_id=model_id)
        self.requests[rid] = state
        if output and len(output) >= state.params.max_tokens:
            # already at its token budget: nothing left to decode
            self._finish(state, "length")
            return rid
        k, v = payload.get("k"), payload.get("v")
        usable = (
            k is not None and v is not None and output
            and int(payload.get("page_size", -1)) == self.ecfg.page_size
            and len(prompt) <= ctx_len < self.ecfg.max_seq_len
            and tuple(k.shape) == (self.cfg.n_layers, k.shape[1],
                                   self.ecfg.page_size,
                                   self.cfg.n_kv_heads,
                                   self.cfg.head_dim)
            and tuple(v.shape) == tuple(k.shape)
            and k.shape[1] >= self.allocator.pages_needed(ctx_len))
        if not usable or not self._inject_pages(state, k, v, ctx_len):
            self.waiting.append(state)  # recompute fallback
        return rid

    def _inject_pages(self, state: RequestState, k, v,
                      ctx_len: int) -> bool:
        slot = self._free_slot()
        if slot < 0:
            return False
        n_kv = self.allocator.pages_needed(ctx_len)
        # headroom for the next decoded token too (mirrors _admit's +1)
        need = self.allocator.pages_needed(ctx_len + 1)
        if not self.allocator.can_allocate(need) and self.prefix_cache:
            self.prefix_cache.evict_for(ctx_len + 1)
        if not self.allocator.can_allocate(need):
            return False
        pages = self.allocator.allocate(need)
        idx = jnp.asarray(pages[:n_kv], jnp.int32)
        self.cache = KVCache(
            self.cache.k.at[:, idx].set(
                jnp.asarray(k[:, :n_kv], self.cache.k.dtype)),
            self.cache.v.at[:, idx].set(
                jnp.asarray(v[:, :n_kv], self.cache.v.dtype)))
        state.slot = slot
        state.ctx_len = ctx_len
        state.prefill_pos = ctx_len
        if not state.first_token_t:
            state.first_token_t = time.perf_counter()
        self.slots[slot] = state
        self.seq_table.assign(slot, pages)
        if self.prefix_cache is not None:
            # shipped pages double as prefix-cache warmth: register the
            # prompt's full pages so future shared-prefix requests on
            # THIS engine skip their prefill too (same insert the
            # chunked prefill path does after filling them itself)
            keys = PrefixCache.page_keys(state.prompt,
                                         self.ecfg.page_size)
            n_reg = min(len(keys), n_kv)
            if n_reg > 0:
                self.prefix_cache.insert(keys[:n_reg], pages[:n_reg])
                state.prompt_page_keys = keys
        return True

    # --- LoRA management (vLLM add_lora/remove_lora analog) ---

    def add_lora(self, name: str, adapter=None, *, seed: int = 0) -> None:
        """Load an adapter into the pool (``adapter`` defaults to a
        fresh zero-delta init at the engine's rank)."""
        if self.lora_pool is None:
            raise ValueError("engine built without lora_rank")
        if adapter is None:
            from .lora import init_lora_adapter

            adapter = init_lora_adapter(
                jax.random.PRNGKey(seed), self.cfg,
                self.ecfg.lora_rank, dtype=self.cfg.dtype)
        self.lora_pool.add(name, adapter)

    def remove_lora(self, name: str) -> None:
        if self.lora_pool is None:
            raise ValueError("engine built without lora_rank")
        users = [s.request_id for s in self.requests.values()
                 if s.model_id == name and not s.finished]
        if users:
            # removal mid-flight would KeyError inside a later step(),
            # killing the whole batch including base-model requests
            raise RuntimeError(
                f"adapter {name!r} is in use by {len(users)} live "
                f"request(s); drain or abort them first")
        self.lora_pool.remove(name)

    # --- metrics ---

    def stats(self) -> Dict[str, Any]:
        out = {
            "running": sum(s is not None for s in self.slots),
            "waiting": len(self.waiting),
            "free_pages": self.allocator.free_pages,
            "total_pages": self.allocator.num_pages - 1,
        }
        if self.spec is not None:
            out["spec"] = self.spec.stats()
        return out
