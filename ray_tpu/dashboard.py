"""Dashboard head: a JSON API + minimal UI over cluster state (ref:
python/ray/dashboard/head.py:65 + modules/* REST routes; the reference
ships a React bundle — here a single self-contained HTML page renders
the same tables from the JSON API, no build step, no assets).

    port = ray_tpu.dashboard.start_dashboard()
    GET /                  — HTML UI (auto-refreshing tables)
    GET /api/nodes /api/actors /api/tasks /api/objects /api/jobs
        /api/cluster_status /api/metrics /api/health /api/stacks
        /api/serve /api/slo /api/profile /api/memory /api/incidents
    GET /metrics           — Prometheus text scrape endpoint
                             (ref: _private/prometheus_exporter.py)
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional

_runner = None
_loop: Optional[asyncio.AbstractEventLoop] = None
_port: Optional[int] = None

_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:0;background:#fafafa;color:#222}
 header{background:#1a237e;color:#fff;padding:10px 20px;display:flex;
        align-items:baseline;gap:16px}
 header h1{font-size:16px;margin:0}
 header span{opacity:.8;font-size:12px}
 main{padding:16px 20px;max-width:1200px}
 section{background:#fff;border:1px solid #e0e0e0;border-radius:6px;
         margin-bottom:16px;padding:12px 16px}
 h2{font-size:13px;text-transform:uppercase;letter-spacing:.05em;
    color:#555;margin:0 0 8px}
 table{border-collapse:collapse;width:100%;font-size:12px}
 th{text-align:left;color:#777;font-weight:600;border-bottom:1px solid #eee;
    padding:3px 10px 3px 0}
 td{border-bottom:1px solid #f3f3f3;padding:3px 10px 3px 0;
    font-family:ui-monospace,monospace;white-space:nowrap;overflow:hidden;
    max-width:260px;text-overflow:ellipsis}
 .pill{display:inline-block;border-radius:9px;padding:0 8px;font-size:11px}
 .ok{background:#e8f5e9;color:#1b5e20}.bad{background:#ffebee;color:#b71c1c}
</style></head><body>
<header><h1>ray_tpu</h1><span id="status"></span>
<span style="margin-left:auto"><a style="color:#c5cae9"
 href="/metrics">/metrics</a></span></header>
<main>
 <section><h2>Cluster</h2><div id="cluster"></div></section>
 <section><h2>Health</h2><div id="health"></div></section>
 <section><h2>Nodes</h2><div id="nodes"></div></section>
 <section><h2>Memory</h2><div id="memory"></div></section>
 <section><h2>Profile</h2>
  <div style="margin-bottom:6px">duration <input id="profdur" value="2"
   size="3">s&nbsp; hz <input id="profhz" value="50" size="4">
   <button onclick="runProfile()">sample</button>
   <span id="profstatus"></span></div>
  <div id="flame"></div></section>
 <section><h2>Actors</h2><div id="actors"></div></section>
 <section><h2>Serve</h2><div id="serve"></div></section>
 <section><h2>SLO</h2><div id="slo"></div></section>
 <section><h2>Train</h2><div id="train"></div></section>
 <section><h2>Incidents</h2><div id="incidents"></div></section>
 <section><h2>Jobs</h2><div id="jobs"></div></section>
 <section><h2>Task summary</h2><div id="tasks"></div></section>
 <section><h2>Events</h2><div id="events"></div></section>
 <section><h2>Task timeline</h2>
  <div style="margin-bottom:6px"><a href="/api/timeline" download="timeline.json">
   download chrome-trace JSON</a> (open in Perfetto)</div>
  <div id="phases" style="margin-bottom:8px"></div>
  <div id="timeline"></div></section>
 <section><h2>Worker logs</h2>
  <select id="lognode"></select> <select id="logfile"></select>
  <button onclick="tailLog()">tail</button>
  <pre id="logview" style="max-height:300px;overflow:auto;background:#111;
   color:#ddd;padding:8px;font-size:11px"></pre></section>
</main>
<script>
const esc=s=>String(s).replace(/[&<>"']/g,c=>({'&':'&amp;','<':'&lt;',
 '>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
// all API data is HTML-escaped; only values wrapped as {__html} (the
// alive/dead pills built below) render raw
const fmt=v=>v&&v.__html?v.__html:
 esc(typeof v==='object'&&v!==null?JSON.stringify(v):String(v));
function table(rows,cols){if(!rows||!rows.length)return'<i>none</i>';
 cols=cols||Object.keys(rows[0]);
 let h='<table><tr>'+cols.map(c=>'<th>'+c+'</th>').join('')+'</tr>';
 for(const r of rows.slice(0,200))
  h+='<tr>'+cols.map(c=>'<td>'+fmt(r[c]??'')+'</td>').join('')+'</tr>';
 return h+'</table>';}
async function j(u){const r=await fetch(u);return r.json();}
const fmtB=n=>{const u=['B','KiB','MiB','GiB','TiB'];let i=0;
 while(Math.abs(n)>=1024&&i<u.length-1){n/=1024;i++;}
 return (i?n.toFixed(1):Math.round(n))+u[i];};
let memNodes={},memHbm={};
async function refresh(){try{
 const cs=await j('/api/cluster_status');
 document.getElementById('cluster').innerHTML=table([{
  nodes:cs.nodes,total:cs.resources_total,
  available:cs.resources_available}]);
 document.getElementById('tasks').innerHTML=
  table(Object.entries(cs.task_summary||{}).map(([k,v])=>({state:k,count:v})));
 const nodes=await j('/api/nodes');
 document.getElementById('nodes').innerHTML=table(nodes.map(n=>({
  id:(n.NodeID||'').slice(0,12),address:n.NodeManagerAddress||n.Address||'',
  alive:{__html:n.Alive?'<span class="pill ok">alive</span>'
                       :'<span class="pill bad">dead</span>'},
  heartbeat:n.HeartbeatAgeS==null?'never':n.HeartbeatAgeS.toFixed(1)+'s ago',
  clock_offset:((n.ClockOffset||0)>=0?'+':'')+(n.ClockOffset||0).toFixed(4)+'s',
  store:(s=>s?fmtB(s.used_bytes||0)+'/'+fmtB(s.capacity_bytes||0)
   +' ('+(s.num_objects||0)+' obj)':'')(memNodes[n.NodeID]),
  hbm:(h=>h?fmtB(h.use)+' on '+h.n+' chip(s)':'')
   (memHbm[(n.NodeID||'').slice(0,12)]),
  resources:n.Resources||{},labels:n.Labels||{}})),
  ['id','address','alive','heartbeat','clock_offset','store','hbm',
   'resources','labels']);
 const actors=await j('/api/actors');
 document.getElementById('actors').innerHTML=table(actors.map(a=>({
  id:(a.actor_id||'').slice(0,12),class:a.class_name,state:a.state,
  name:a.name||'',node:(a.node_id||'').slice(0,12)})));
 const jobs=await j('/api/jobs');
 document.getElementById('jobs').innerHTML=table(jobs);
 const ev=await j('/api/events?limit=30');
 document.getElementById('events').innerHTML=table(
  ev.reverse().map(e=>({
   time:new Date(e.timestamp*1000).toLocaleTimeString(),
   source:e.source,severity:e.severity,message:e.message})),
  ['time','source','severity','message']);
 document.getElementById('status').textContent=
  'updated '+new Date().toLocaleTimeString();
}catch(e){document.getElementById('status').textContent='error: '+e;}}
async function refreshHealth(){try{
 const h=await j('/api/health');
 const st=h.stalls||{};
 const rows=[];
 for(const t of st.tasks||[])rows.push({kind:'task_stall',
  what:'task '+(t.task_id||'').slice(0,12)+' ('+(t.fn||'?')+')',
  detail:'RUNNING '+(t.age_s||0).toFixed(1)+'s (threshold '
   +(t.threshold_s||0).toFixed(1)+'s) pid '+t.pid,
  node:(t.node_id||'').slice(0,12)});
 for(const t of st.transfers||[])rows.push({kind:'transfer_stall',
  what:'pull '+(t.object_id||'').slice(0,12),
  detail:'no progress '+(t.stalled_for_s||0).toFixed(1)+'s ('
   +(t.watermark||0)+'/'+(t.size||0)+' bytes)',
  node:(t.node_id||'').slice(0,12)});
 for(const c of st.collectives||[])rows.push({kind:'collective_stall',
  what:(c.group||'')+' step '+c.step+' ('+(c.op||'')+')',
  detail:'missing ranks '+JSON.stringify(c.missing_ranks||[])+' of '
   +c.size,node:(c.missing_hosts||[]).join(',')});
 let html=rows.length?table(rows,['kind','what','detail','node'])
  :'<span class="pill ok">no stalls detected</span>';
 const sc=h.straggler_scores||[];
 if(sc.length)html+='<div style="margin-top:8px">straggler scores</div>'
  +table(sc.map(s=>({host:s.host,score:(s.score||0).toFixed(2),
   ema_lateness_s:(s.ema_lateness_s||0).toFixed(4),
   worst:(s.worst_count||0)+'/'+(s.steps||0)})),
   ['host','score','ema_lateness_s','worst']);
 document.getElementById('health').innerHTML=html;
}catch(e){}}
async function refreshServe(){try{
 const s=await j('/api/serve');
 const deps=s.deployments||[];
 let html=deps.length?table(deps.map(d=>({
  name:d.name,replicas:d.num_replicas+'/'+d.target_replicas,
  pools:d.pools?Object.entries(d.pools).map(([p,n])=>p+'='+n).join(' '):'',
  prefix_summaries:d.prefix_summaries||0})),
  ['name','replicas','pools','prefix_summaries'])
  :'<i>no deployments</i>';
 const rt=s.routing||[];
 if(rt.length)html+='<div style="margin-top:8px">fleet KV routing</div>'
  +table(rt.map(e=>({metric:e.name,value:e.value,
   tags:Object.entries(e.tags||{}).map(([k,v])=>k+'='+v).join(' ')})),
   ['metric','value','tags']);
 document.getElementById('serve').innerHTML=html;
}catch(e){}}
async function refreshSlo(){try{
 const s=await j('/api/slo');
 if(!s.enabled){document.getElementById('slo').innerHTML=
  '<i>slo monitor disabled</i>';return;}
 const specs=s.specs||[];
 // attainment history renders as a unicode sparkline per spec
 const bars='▁▂▃▄▅▆▇█';
 const spark=h=>{const v=(h||[]).map(x=>x.attainment).filter(x=>x!=null);
  if(!v.length)return'';const lo=Math.min(...v),hi=Math.max(...v);
  return v.slice(-40).map(x=>bars[hi>lo?
   Math.round((x-lo)/(hi-lo)*(bars.length-1)):bars.length-1]).join('');};
 let html=specs.length?table(specs.map(x=>({
  slo:x.spec,
  alert:{__html:x.alert==='ok'?'<span class="pill ok">ok</span>'
   :'<span class="pill bad">'+esc(x.alert)+'</span>'},
  attainment:x.attainment==null?'-':(x.attainment*100).toFixed(3)+'%',
  objective:(x.objective*100)+'%',
  achieved:x.achieved==null?'':(x.achieved*1000).toFixed(1)+'ms',
  events:x.total||0,
  burn:Object.entries(x.burns||{}).map(([k,v])=>
   k+' '+v.short+'x/'+v.long+'x').join(' '),
  history:spark(x.history)})),
  ['slo','alert','attainment','objective','achieved','events','burn',
   'history'])
  :'<i>no slo specs installed</i>';
 const ev=s.events||[];
 if(ev.length)html+='<div style="margin-top:8px">burn-rate alerts</div>'
  +table(ev.slice().reverse().slice(0,10).map(e=>({
   time:new Date(e.timestamp*1000).toLocaleTimeString(),
   severity:e.severity,message:e.message})),
   ['time','severity','message']);
 document.getElementById('slo').innerHTML=html;
}catch(e){}}
async function refreshTrain(){try{
 const t=await j('/api/train');
 const jobs=t.jobs||[];
 if(!jobs.length){document.getElementById('train').innerHTML=
  '<i>no training jobs reporting</i>';return;}
 let html=table(jobs.map(x=>({
  job:x.job,world:x.world_size,chips:x.chips,steps:x.steps,
  goodput:x.goodput_fraction==null?'-'
   :(x.goodput_fraction*100).toFixed(1)+'%',
  mfu:x.mfu?(x.mfu*100).toFixed(1)+'%':'-',
  'tok/s/chip':x.tok_per_s_per_chip?
   Math.round(x.tok_per_s_per_chip):'-',
  compiles:(x.compile_count||0)+' cold / '+(x.cache_hit_count||0)
   +' hit / '+(x.recompile_count||0)+' re',
  rework:x.rework_steps||0,restarts:x.restarts||0})),
  ['job','world','chips','steps','goodput','mfu','tok/s/chip',
   'compiles','rework','restarts']);
 for(const x of jobs){
  const bad=Object.entries(x.badput_s||{}).sort((a,b)=>b[1]-a[1]);
  const tot=bad.reduce((s,[,v])=>s+v,0);
  if(bad.length)html+='<div style="margin-top:8px">badput — '
   +esc(x.job)+' ('+tot.toFixed(2)+' chip-s)</div>'
   +table(bad.map(([cause,s])=>({cause,seconds:s.toFixed(3),
    share:tot>0?(s/tot*100).toFixed(1)+'%':'-',
    bar:'#'.repeat(Math.max(1,Math.round((tot>0?s/tot:0)*30)))}),
   ),['cause','seconds','share','bar']);
  const skew=Object.entries(x.rank_skew||{}).sort((a,b)=>b[1]-a[1]);
  if(skew.length){const worst=skew[0][1]||1e-9;
   html+='<div style="margin-top:8px">rank skew — '+esc(x.job)+'</div>'
   +table(skew.map(([who,s])=>({rank:who,ema_wait:s.toFixed(4)+'s',
    bar:'#'.repeat(Math.max(0,Math.round(s/worst*20)))})),
   ['rank','ema_wait','bar']);}}
 document.getElementById('train').innerHTML=html;
}catch(e){}}
async function refreshIncidents(){try{
 const inc=await j('/api/incidents');
 const bundles=inc.bundles||[];
 let html=bundles.length?table(bundles.slice().reverse().slice(0,15).map(b=>({
  time:new Date((b.written_at||0)*1000).toLocaleTimeString(),
  role:{__html:'<span class="pill bad">'+esc(b.role||'?')
   +' pid '+esc(b.pid||'?')+'</span>'},
  reason:(b.reason||'')+(b.signal_name?' ('+b.signal_name+')':''),
  node:(b.node_id||'').slice(0,12),
  inflight:(b.inflight||[]).slice(0,3).map(r=>
   (r.task_id||r.request_id||r.lease_id||r.kind||'?')
    .toString().slice(0,12)).join(' ')||'',
  bundle:b.path||''})),
  ['time','role','reason','node','inflight','bundle'])
  :'<span class="pill ok">no crash bundles</span>';
 const cc=inc.crash_counts||[];
 if(cc.length)html+='<div style="margin-top:8px">crash totals</div>'
  +table(cc.map(c=>({node:(c.node||'').slice(0,12),role:c.role||'',
   reason:c.reason||'',count:c.count||0})));
 const ev=inc.events||[];
 if(ev.length)html+='<div style="margin-top:8px">incident events</div>'
  +table(ev.slice().reverse().slice(0,10).map(e=>({
   time:new Date(e.timestamp*1000).toLocaleTimeString(),
   severity:e.severity,message:e.message,
   artifacts:(e.artifacts||[]).join(' ')})),
   ['time','severity','message','artifacts']);
 document.getElementById('incidents').innerHTML=html;
}catch(e){}}
async function refreshTimeline(){try{
 const s=await j('/api/summary');
 const ph=s.phases||{};
 document.getElementById('phases').innerHTML=table([{
  tasks:s.tasks_with_transitions||0,
  wall_s:(s.wall_time_s||0).toFixed(3),
  scheduling_s:(ph.scheduling||0).toFixed(3),
  dep_fetch_s:(ph.dep_fetch||0).toFixed(3),
  execution_s:(ph.execution||0).toFixed(3),
  transfer_s:(ph.transfer||0).toFixed(3)}]);
 let tl=await j('/api/timeline');
 // duration slices only: metadata (ph M) and flow (s/f) records carry
 // no ts/dur and would render as NaN rows
 tl=tl.filter(e=>e.ph==='X');
 tl.sort((a,b)=>b.ts-a.ts);
 document.getElementById('timeline').innerHTML=table(tl.slice(0,60).map(e=>({
  task:e.name,start:new Date(e.ts/1000).toLocaleTimeString(),
  dur_ms:(e.dur/1000).toFixed(1),
  node:e.args&&e.args.node||'',worker:e.args&&e.args.worker||'',
  phase:e.args&&e.args.phase||'',state:e.args&&e.args.state||'',
  error:e.args&&e.args.error||''})),
  ['task','start','dur_ms','node','worker','phase','state','error']);
}catch(e){}}
async function refreshMemory(){try{
 const m=await j('/api/memory');
 memNodes={};for(const nd of m.nodes||[])memNodes[nd.node_id]=nd;
 const cl=m.cluster||{};
 let html=table([{live:fmtB(cl.used_bytes||0),
  spilled:fmtB(cl.spill_bytes||0),objects:cl.num_objects||0,
  attributed:((cl.attributed_fraction||0)*100).toFixed(1)+'%'}]);
 const bt=Object.entries(cl.by_ref_type||{}).sort((a,b)=>b[1]-a[1]);
 if(bt.length)html+='<div style="margin-top:8px">by ref-type</div>'
  +table(bt.map(([t,b])=>({ref_type:t,bytes:fmtB(b)})));
 const ls=m.leak_suspects||[];
 if(ls.length)html+='<div style="margin-top:8px"><span class="pill bad">'
  +ls.length+' leak suspect(s)</span></div>'
  +table(ls.map(o=>({object:(o.object_id||'').slice(0,16),
   size:fmtB(o.size||0),pinned:o.pinned,age_s:o.age_s,
   node:(o.node_id||'').slice(0,12)})));
 const ws=m.workers||[];
 if(ws.length)html+='<div style="margin-top:8px">worker heap</div>'
  +table(ws.map(w=>({pid:w.pid,mode:w.mode||'',
   heap:fmtB((w.heap||{}).current_bytes||0)
    +' ('+((w.heap||{}).kind||'?')+')',
   inflight:w.num_inflight_tasks||0,
   hbm:(w.hbm||[]).length?fmtB((w.hbm||[]).reduce(
    (a,d)=>a+(d.bytes_in_use||0),0)):''})),
   ['pid','mode','heap','inflight','hbm']);
 document.getElementById('memory').innerHTML=html;
 memHbm={};
 const mts=await j('/api/metrics');
 for(const e of mts||[]){if(e.name!=='hbm_bytes_in_use')continue;
  const t=(e.tags||{}).node||'';const h=memHbm[t]||{use:0,n:0};
  h.use+=e.value||0;h.n+=1;memHbm[t]=h;}
}catch(e){}}
async function runProfile(){
 const d=document.getElementById('profdur').value||2;
 const hz=document.getElementById('profhz').value||50;
 document.getElementById('profstatus').textContent='sampling '+d+'s...';
 try{
  const p=await j('/api/profile?duration='+encodeURIComponent(d)
   +'&hz='+encodeURIComponent(hz));
  document.getElementById('profstatus').textContent=
   (p.samples||0)+' samples from '+(p.workers||0)+' worker(s)';
  const rows=Object.entries(p.wall||{}).sort((a,b)=>b[1]-a[1]).slice(0,25);
  const max=rows.length?rows[0][1]:1;
  let html='';
  const bc=Object.entries(p.by_class||{}).sort((a,b)=>b[1]-a[1]);
  if(bc.length)html+=table(bc.map(([c,v])=>({class:c,samples:v})))
   +'<div style="margin-top:8px">top stacks (wall, bar = share)</div>';
  for(const[k,v]of rows){
   const leaf=k.split(';').pop();
   html+='<div style="margin:1px 0;background:#ffe0b2;white-space:nowrap;'
    +'overflow:hidden;text-overflow:ellipsis;'
    +'font:11px ui-monospace,monospace;padding:1px 4px;width:'
    +Math.max(2,Math.round(100*v/max))+'%" title="'+esc(k)+'">'
    +esc(leaf)+' ('+v+')</div>';}
  document.getElementById('flame').innerHTML=html||'<i>no samples</i>';
 }catch(e){
  document.getElementById('profstatus').textContent='error: '+e;}}
async function refreshLogs(){try{
 const nodes=await j('/api/nodes');
 const sel=document.getElementById('lognode');
 const cur=sel.value;
 sel.innerHTML=nodes.filter(n=>n.Alive).map(n=>
  '<option value="'+esc(n.NodeID)+'">'+esc((n.NodeID||'').slice(0,12))
  +'</option>').join('');
 if(cur)sel.value=cur;
 const files=await j('/api/logs?node_id='+encodeURIComponent(sel.value||''));
 const fsel=document.getElementById('logfile');
 const fcur=fsel.value;
 fsel.innerHTML=files.map(f=>'<option>'+esc(f)+'</option>').join('');
 if(fcur)fsel.value=fcur;
}catch(e){}}
async function tailLog(){
 const n=document.getElementById('lognode').value;
 const f=document.getElementById('logfile').value;
 if(!f)return;
 const r=await fetch('/api/logs/tail?node_id='+encodeURIComponent(n)
  +'&file='+encodeURIComponent(f)+'&lines=200');
 document.getElementById('logview').textContent=await r.text();}
refresh();refreshTimeline();refreshLogs();refreshHealth();refreshServe();
refreshSlo();refreshMemory();refreshIncidents();refreshTrain();
setInterval(refresh,5000);setInterval(refreshTimeline,10000);
setInterval(refreshLogs,15000);setInterval(refreshHealth,5000);
setInterval(refreshServe,5000);setInterval(refreshSlo,5000);
setInterval(refreshMemory,10000);setInterval(refreshIncidents,10000);
setInterval(refreshTrain,5000);
</script></body></html>
"""


def _routes():
    from aiohttp import web

    from . import available_resources, cluster_resources, nodes
    from .util import state as state_api

    def _json(data):
        return web.json_response(data, dumps=_dumps)

    def _dumps(obj):
        import json

        return json.dumps(obj, default=str)

    async def api_nodes(_req):
        return _json(nodes())

    async def api_actors(_req):
        return _json(state_api.list_actors())

    async def api_tasks(_req):
        return _json(state_api.list_tasks())

    async def api_objects(_req):
        return _json(state_api.list_objects())

    async def api_metrics(_req):
        return _json(state_api.get_metrics())

    async def api_events(req):
        return _json(state_api.list_cluster_events(
            source=req.query.get("source"),
            severity=req.query.get("severity"),
            limit=int(req.query.get("limit", 100))))

    async def api_jobs(_req):
        from .job_submission import JobSubmissionClient

        client = JobSubmissionClient()
        return _json([j.__dict__ for j in client.list_jobs()])

    async def api_cluster_status(_req):
        return _json({
            "nodes": len([n for n in nodes() if n["Alive"]]),
            "resources_total": cluster_resources(),
            "resources_available": available_resources(),
            "task_summary": state_api.summarize_tasks(),
        })

    async def api_timeline(_req):
        from .util import tracing

        return _json(tracing.timeline())

    async def api_summary(_req):
        return _json(state_api.summarize_tasks(breakdown=True))

    async def api_health(_req):
        return _json({
            "stalls": state_api.list_stalls(),
            "straggler_scores": state_api.straggler_scores(),
            "events": state_api.list_cluster_events(
                source="stall_sentinel", limit=50),
        })

    async def api_serve(_req):
        """Serve deployments (pools, prefix-summary coverage) + fleet-KV
        routing counters. Read-only: never creates the controller."""
        from . import get, get_actor
        from .serve.controller import CONTROLLER_NAME

        deployments = []
        try:
            controller = get_actor(CONTROLLER_NAME)
            deployments = get(controller.list_deployments.remote(),
                              timeout=15)
        except ValueError:
            pass  # no serve controller running: empty panel
        rows = []
        try:
            for name in ("serve_prefix_route_hits",
                         "serve_prefix_route_misses",
                         "serve_kv_handoff_bytes_total",
                         "serve_kv_handoff_retries_total",
                         "serve_hedges_launched", "serve_hedges_won",
                         "llm_spec_draft_tokens_total",
                         "llm_spec_accepted_tokens_total",
                         "llm_spec_acceptance_ratio"):
                rows.extend(state_api.get_metrics(name))
        except Exception:  # noqa: BLE001 — metrics plane is optional
            rows = []
        return _json({"deployments": deployments, "routing": rows})

    async def api_slo(_req):
        """SLO plane: per-spec attainment/burn/alert records (with the
        attainment history ring) + recent burn-rate alert events."""
        status = {}
        try:
            status = state_api.slo_status()
        except Exception:  # noqa: BLE001 — SLO plane is optional
            status = {"enabled": False, "specs": []}
        events, events_error = [], None
        try:
            events = state_api.list_cluster_events(source="slo", limit=50)
        except Exception as e:  # noqa: BLE001 — degrade panel, keep page
            events_error = repr(e)
        payload = {**status, "events": events}
        if events_error is not None:
            payload["events_error"] = events_error
        return _json(payload)

    async def api_train(req):
        """Training goodput plane: per-job ledger records (goodput %,
        badput-by-cause, MFU, tok/s/chip, compile counts, rank skew,
        recent-step ring) from the GCS goodput ledgers."""
        import dataclasses

        try:
            status = state_api.train_status(
                job=req.query.get("job") or None)
            jobs = [dataclasses.asdict(x) if dataclasses.is_dataclass(x)
                    else x for x in status.get("jobs", [])]
        except Exception:  # noqa: BLE001 — train plane is optional
            jobs = []
        return _json({"jobs": jobs})

    async def api_incidents(_req):
        """Black-box plane: crash bundles swept from dead processes,
        incident events (process_crash / node death / burn alerts with
        self-diagnosis artifacts), per-node crash totals."""
        try:
            return _json(state_api.list_incidents())
        except Exception:  # noqa: BLE001 — black-box plane is optional
            return _json({"bundles": [], "events": [], "crash_counts": []})

    async def api_stacks(req):
        node = req.query.get("node_id") or None
        return _json(state_api.dump_stacks(node_id=node))

    async def api_profile(req):
        """On-demand cluster sampling burst → merged folded stacks (the
        flamegraph panel's data). Blocks this handler for the sampling
        window, so the duration is clamped."""
        duration = min(float(req.query.get("duration", 2.0)), 30.0)
        hz = float(req.query.get("hz", 50.0))
        node = req.query.get("node_id") or None
        return _json(state_api.profile_cluster(
            duration_s=duration, hz=hz, node_id=node))

    async def api_memory(_req):
        """Cluster memory attribution: store bytes by ref-type, leak
        suspects, per-worker heap, per-chip HBM."""
        return _json(state_api.memory_report())

    async def api_logs(req):
        node = req.query.get("node_id") or None
        return _json(state_api.list_logs(node))

    async def api_log_tail(req):
        node = req.query.get("node_id") or None
        filename = req.query["file"]
        lines = int(req.query.get("lines", 200))
        text = state_api.get_log(filename, node, tail_bytes=lines * 120)
        return web.Response(text=text or "", content_type="text/plain",
                            charset="utf-8")

    async def prometheus_metrics(_req):
        from ._private.prometheus import render_cluster

        return web.Response(text=render_cluster(),
                            content_type="text/plain", charset="utf-8")

    async def index(_req):
        return web.Response(text=_UI_HTML, content_type="text/html")

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/metrics", prometheus_metrics)
    app.router.add_get("/api/nodes", api_nodes)
    app.router.add_get("/api/actors", api_actors)
    app.router.add_get("/api/tasks", api_tasks)
    app.router.add_get("/api/objects", api_objects)
    app.router.add_get("/api/jobs", api_jobs)
    app.router.add_get("/api/metrics", api_metrics)
    app.router.add_get("/api/events", api_events)
    app.router.add_get("/api/cluster_status", api_cluster_status)
    app.router.add_get("/api/timeline", api_timeline)
    app.router.add_get("/api/summary", api_summary)
    app.router.add_get("/api/health", api_health)
    app.router.add_get("/api/serve", api_serve)
    app.router.add_get("/api/slo", api_slo)
    app.router.add_get("/api/train", api_train)
    app.router.add_get("/api/incidents", api_incidents)
    app.router.add_get("/api/stacks", api_stacks)
    app.router.add_get("/api/profile", api_profile)
    app.router.add_get("/api/memory", api_memory)
    app.router.add_get("/api/logs", api_logs)
    app.router.add_get("/api/logs/tail", api_log_tail)
    return app


def start_dashboard(port: int = 0) -> int:
    """Serve the API from a background thread; returns the bound port."""
    global _runner, _loop, _port
    if _port is not None:
        return _port
    from aiohttp import web

    started = threading.Event()

    def _serve():
        global _runner, _loop, _port
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        _loop = loop

        async def _up():
            global _runner, _port
            _runner = web.AppRunner(_routes())
            await _runner.setup()
            site = web.TCPSite(_runner, "127.0.0.1", port)
            await site.start()
            _port = site._server.sockets[0].getsockname()[1]
            started.set()

        loop.run_until_complete(_up())
        loop.run_forever()

    threading.Thread(target=_serve, daemon=True,
                     name="ray_tpu_dashboard").start()
    if not started.wait(timeout=30):
        raise RuntimeError("dashboard failed to start")
    return _port


def stop_dashboard() -> None:
    global _runner, _loop, _port
    if _loop is not None:
        loop, runner = _loop, _runner

        async def _down():
            if runner is not None:
                await runner.cleanup()
            loop.stop()

        asyncio.run_coroutine_threadsafe(_down(), loop)
    _runner = _loop = _port = None
