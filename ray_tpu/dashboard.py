"""Dashboard head: a JSON API over cluster state (ref:
python/ray/dashboard/head.py:65 + modules/* REST routes; the aiohttp app
serves the same state the reference UI reads — nodes, actors, tasks,
objects, jobs, metrics — without shipping a frontend bundle).

    port = ray_tpu.dashboard.start_dashboard()
    GET /api/nodes /api/actors /api/tasks /api/objects /api/jobs
        /api/cluster_status /api/metrics
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional

_runner = None
_loop: Optional[asyncio.AbstractEventLoop] = None
_port: Optional[int] = None


def _routes():
    from aiohttp import web

    from . import available_resources, cluster_resources, nodes
    from .util import state as state_api

    def _json(data):
        return web.json_response(data, dumps=_dumps)

    def _dumps(obj):
        import json

        return json.dumps(obj, default=str)

    async def api_nodes(_req):
        return _json(nodes())

    async def api_actors(_req):
        return _json(state_api.list_actors())

    async def api_tasks(_req):
        return _json(state_api.list_tasks())

    async def api_objects(_req):
        return _json(state_api.list_objects())

    async def api_metrics(_req):
        return _json(state_api.get_metrics())

    async def api_jobs(_req):
        from .job_submission import JobSubmissionClient

        client = JobSubmissionClient()
        return _json([j.__dict__ for j in client.list_jobs()])

    async def api_cluster_status(_req):
        return _json({
            "nodes": len([n for n in nodes() if n["Alive"]]),
            "resources_total": cluster_resources(),
            "resources_available": available_resources(),
            "task_summary": state_api.summarize_tasks(),
        })

    app = web.Application()
    app.router.add_get("/api/nodes", api_nodes)
    app.router.add_get("/api/actors", api_actors)
    app.router.add_get("/api/tasks", api_tasks)
    app.router.add_get("/api/objects", api_objects)
    app.router.add_get("/api/jobs", api_jobs)
    app.router.add_get("/api/metrics", api_metrics)
    app.router.add_get("/api/cluster_status", api_cluster_status)
    return app


def start_dashboard(port: int = 0) -> int:
    """Serve the API from a background thread; returns the bound port."""
    global _runner, _loop, _port
    if _port is not None:
        return _port
    from aiohttp import web

    started = threading.Event()

    def _serve():
        global _runner, _loop, _port
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        _loop = loop

        async def _up():
            global _runner, _port
            _runner = web.AppRunner(_routes())
            await _runner.setup()
            site = web.TCPSite(_runner, "127.0.0.1", port)
            await site.start()
            _port = site._server.sockets[0].getsockname()[1]
            started.set()

        loop.run_until_complete(_up())
        loop.run_forever()

    threading.Thread(target=_serve, daemon=True,
                     name="ray_tpu_dashboard").start()
    if not started.wait(timeout=30):
        raise RuntimeError("dashboard failed to start")
    return _port


def stop_dashboard() -> None:
    global _runner, _loop, _port
    if _loop is not None:
        loop, runner = _loop, _runner

        async def _down():
            if runner is not None:
                await runner.cleanup()
            loop.stop()

        asyncio.run_coroutine_threadsafe(_down(), loop)
    _runner = _loop = _port = None
