"""ActorClass and ActorHandle (ref: python/ray/actor.py — remote:215, _remote:900)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ._private.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = dict(options or {})

    def remote(self, *args, **kwargs):
        from . import _worker_api

        refs = _worker_api.core().submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs, self._options)
        if self._options.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def options(self, **new_options) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(new_options)
        return ActorMethod(self._handle, self._method_name, merged)

    def bind(self, *args, **kwargs):
        from .dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs, self._options)


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        # cache on the instance: hot actor-call loops touch the same
        # method attribute thousands of times (default-options methods
        # are stateless; .options() still returns fresh instances)
        method = ActorMethod(self, name)
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:16]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id


def _rtpu_dyn_call(self, fn_blob: bytes, *args, **kwargs):
    """Injected universal method: run a pickled function against the
    actor instance (the compiled-DAG exec-loop entry point; ref:
    actor.py __ray_call__ injection in the reference)."""
    import cloudpickle

    fn = cloudpickle.loads(fn_blob)
    return fn(self, *args, **kwargs)


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        self.__name__ = getattr(cls, "__name__", "ActorClass")
        if not hasattr(cls, "_rtpu_dyn_call"):
            try:
                cls._rtpu_dyn_call = _rtpu_dyn_call
            except (AttributeError, TypeError):
                pass  # frozen/extension classes: compiled DAGs unsupported

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use {self.__name__}.remote(...)"
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        from . import _worker_api

        actor_id = _worker_api.core().submit_actor_creation(
            self._cls, args, kwargs, self._options)
        return ActorHandle(actor_id, self.__name__)

    def options(self, **new_options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(new_options)
        return ActorClass(self._cls, merged)

    def bind(self, *args, **kwargs):
        from .dag import ClassNode

        return ClassNode(self, args, kwargs, self._options)
