"""HuggingFace checkpoint interop for the Llama family.

The reference reaches HF weights through its integration layers (ref:
python/ray/train/huggingface/, python/ray/llm/.. vLLM engine weight
loading); here the mapping is native: safetensors shards <-> the stacked
jax param tree models/llama.py trains and serves. This is the door real
Llama-3 weights walk through to enter the framework.

Layout notes (checked against transformers' LlamaForCausalLM):
  * HF linears store (out_features, in_features); our kernels store
    (in, out) [+ head split], so every projection transposes on import.
  * Our rotary (ops/rotary.py) is the half-split GPT-NeoX convention —
    the SAME one HF safetensors use — so q/k need no column permutation
    (Meta's original interleaved layout would).
  * Our per-layer params are stacked on a leading "layers" axis (scan);
    HF keeps one tensor per layer. Import stacks, export unstacks.
  * `tie_word_embeddings` checkpoints omit lm_head: it becomes
    embed.T, exactly how HF ties them.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig

__all__ = ["config_from_hf", "config_to_hf", "load_hf_checkpoint",
           "save_hf_checkpoint"]


# ---------------------------------------------------------------------------
# safetensors IO, implemented directly over numpy/ml_dtypes.
#
# The format is deliberately trivial (u64 header length + JSON header of
# {name: {dtype, shape, data_offsets}} + raw row-major bytes), and doing
# it by hand avoids a real landed bug: safetensors' flax backend reads
# the XLA device buffer's raw bytes, whose layout XLA may choose to be
# non-row-major for larger 2-D arrays — save+load through that backend
# silently transposes tensors (verified in this environment: a (256,64)
# f32 round-trips transposed while a (3,4) survives). np.asarray()
# performs the layout-correct copy; these helpers build on that.
# ---------------------------------------------------------------------------

_ST_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _st_name(dtype) -> str:
    import ml_dtypes

    if dtype == ml_dtypes.bfloat16:
        return "BF16"
    for name, np_dtype in _ST_DTYPES.items():
        if dtype == np_dtype:
            return name
    raise ValueError(f"unsupported safetensors dtype {dtype}")


def _st_dtype(name: str):
    import ml_dtypes

    if name == "BF16":
        return ml_dtypes.bfloat16
    return np.dtype(_ST_DTYPES[name])


def write_safetensors(tensors: Dict[str, Any], path: str) -> None:
    header: Dict[str, Any] = {}
    offset = 0
    arrays = []
    for name, value in tensors.items():
        arr = np.ascontiguousarray(np.asarray(value))
        nbytes = arr.nbytes
        header[name] = {"dtype": _st_name(arr.dtype),
                        "shape": list(arr.shape),
                        "data_offsets": [offset, offset + nbytes]}
        arrays.append(arr)
        offset += nbytes
    blob = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(blob).to_bytes(8, "little"))
        f.write(blob)
        for arr in arrays:
            f.write(arr.tobytes())


def read_safetensors(path: str) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        header_len = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(header_len))
        base = 8 + header_len
        out: Dict[str, np.ndarray] = {}
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            start, end = meta["data_offsets"]
            f.seek(base + start)
            buf = f.read(end - start)
            out[name] = np.frombuffer(
                buf, dtype=_st_dtype(meta["dtype"])
            ).reshape(meta["shape"])
    return out


def config_from_hf(hf: Dict[str, Any], **overrides) -> LlamaConfig:
    cfg = LlamaConfig(
        vocab=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        mlp_dim=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 500000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
    )
    if overrides:
        cfg = LlamaConfig(**{**cfg.__dict__, **overrides})
    return cfg


def config_to_hf(cfg: LlamaConfig) -> Dict[str, Any]:
    return {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": cfg.vocab,
        "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.mlp_dim,
        "max_position_embeddings": cfg.max_seq,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "head_dim": cfg.head_dim,
        "tie_word_embeddings": False,
        "torch_dtype": "bfloat16",
    }


def _load_shards(path: str) -> Dict[str, Any]:
    """All tensors of a single-file or index-sharded safetensors
    checkpoint, as a flat {hf_name: numpy array} dict."""
    index_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        shards = sorted(set(weight_map.values()))
    else:
        shards = ["model.safetensors"]
    tensors: Dict[str, Any] = {}
    for shard in shards:
        tensors.update(read_safetensors(os.path.join(path, shard)))
    return tensors


def load_hf_checkpoint(path: str, dtype: Optional[Any] = None,
                       quantize: Optional[str] = None,
                       **config_overrides) -> Tuple[Dict, LlamaConfig]:
    """Import an HF-format Llama checkpoint directory -> (params, cfg).

    `path` holds config.json + model.safetensors (or sharded files with
    an index). `dtype` overrides the storage dtype (default: the
    config's, bf16). ``quantize="int8"`` quantizes every projection +
    embedding + lm_head to per-output-channel int8 ON THE HOST before
    anything touches the device — the path that fits Llama-3-8B
    (16.1 GB bf16) onto one 16 GB chip as 8.0 GB of int8 (the reference
    reaches quantized serving only via vLLM engine_kwargs —
    vllm_models.py:59; here it is native, ops/quant.py)."""
    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantize mode {quantize!r}")
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    if dtype is not None:
        config_overrides.setdefault("dtype", dtype)
    cfg = config_from_hf(hf_cfg, **config_overrides)
    t = _load_shards(path)
    d, h, hkv, hd = cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    # quantizing: assemble on the HOST (numpy) so the full-precision
    # tree never occupies device HBM; otherwise straight to device
    if quantize is None:
        cast = lambda x: jnp.asarray(x, cfg.dtype)  # noqa: E731
        xp = jnp
    else:
        cast = lambda x: np.asarray(x, cfg.dtype)  # noqa: E731
        xp = np

    def stack(fmt: str):
        return [t[fmt.format(i)] for i in range(cfg.n_layers)]

    def proj(fmt: str, shape):
        # HF (out, in) -> ours (in, out[, split head dims])
        return cast(xp.stack(
            [w.T.reshape(shape) for w in stack(fmt)]))

    layers = {
        "attn_norm": cast(xp.stack(
            stack("model.layers.{}.input_layernorm.weight"))),
        "wq": proj("model.layers.{}.self_attn.q_proj.weight", (d, h, hd)),
        "wk": proj("model.layers.{}.self_attn.k_proj.weight", (d, hkv, hd)),
        "wv": proj("model.layers.{}.self_attn.v_proj.weight", (d, hkv, hd)),
        # o_proj is (d, h*hd): transpose -> (h*hd, d) -> (h, hd, d)
        "wo": proj("model.layers.{}.self_attn.o_proj.weight", (h, hd, d)),
        "mlp_norm": cast(xp.stack(
            stack("model.layers.{}.post_attention_layernorm.weight"))),
        "w_gate": proj("model.layers.{}.mlp.gate_proj.weight",
                       (d, cfg.mlp_dim)),
        "w_up": proj("model.layers.{}.mlp.up_proj.weight",
                     (d, cfg.mlp_dim)),
        "w_down": proj("model.layers.{}.mlp.down_proj.weight",
                       (cfg.mlp_dim, d)),
    }
    embed = cast(t["model.embed_tokens.weight"])
    if "lm_head.weight" in t:
        lm_head = cast(t["lm_head.weight"].T)
    else:  # tie_word_embeddings
        lm_head = embed.T
    params = {
        "embed": embed,
        "layers": layers,
        "final_norm": cast(t["model.norm.weight"]),
        "lm_head": lm_head,
    }
    if quantize == "int8":
        import jax

        from ..ops.quant import quantize_params

        params = quantize_params(params, cfg)
        params = jax.tree.map(jnp.asarray, params)
    return params, cfg


def save_hf_checkpoint(params: Dict, cfg: LlamaConfig, path: str) -> None:
    """Export params to an HF-format directory (config.json +
    model.safetensors) loadable by transformers/vLLM — and by
    load_hf_checkpoint for the round-trip test."""

    if cfg.n_experts:
        raise NotImplementedError(
            "HF export for MoE configs is not wired up (mixtral-format "
            "expert naming differs); dense Llama only")
    os.makedirs(path, exist_ok=True)
    d = cfg.dim
    t: Dict[str, Any] = {
        "model.embed_tokens.weight": params["embed"],
        "model.norm.weight": params["final_norm"],
        "lm_head.weight": params["lm_head"].T,
    }
    lp = params["layers"]
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        t[pre + "input_layernorm.weight"] = lp["attn_norm"][i]
        t[pre + "self_attn.q_proj.weight"] = \
            lp["wq"][i].reshape(d, -1).T
        t[pre + "self_attn.k_proj.weight"] = \
            lp["wk"][i].reshape(d, -1).T
        t[pre + "self_attn.v_proj.weight"] = \
            lp["wv"][i].reshape(d, -1).T
        t[pre + "self_attn.o_proj.weight"] = \
            lp["wo"][i].reshape(-1, d).T
        t[pre + "post_attention_layernorm.weight"] = lp["mlp_norm"][i]
        t[pre + "mlp.gate_proj.weight"] = lp["w_gate"][i].T
        t[pre + "mlp.up_proj.weight"] = lp["w_up"][i].T
        t[pre + "mlp.down_proj.weight"] = lp["w_down"][i].T
    write_safetensors(t, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config_to_hf(cfg), f, indent=2)
