"""Vision models: functional jax ResNet for the image-training path
(ref: the reference's Train image benchmarks — torch ResNet at
doc/source/train/benchmarks.rst:36-44; here the model is native jax so
the same make_train_step / Data streaming_split machinery drives it).

TPU choices: GroupNorm instead of BatchNorm (stateless — no running
statistics to thread through pjit or sync across data-parallel
replicas), NHWC layout (XLA's preferred conv layout on TPU), bf16
params with f32 normalization/loss.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    # channels per stage; depths = residual blocks per stage
    channels: Tuple[int, ...] = (64, 128, 256, 512)
    depths: Tuple[int, ...] = (2, 2, 2, 2)       # ResNet-18 shape
    groups: int = 8                              # GroupNorm groups
    stem_kernel: int = 3                         # 3 for CIFAR-size, 7 ImageNet
    dtype: Any = jnp.bfloat16

    def n_params(self) -> int:
        leaves = jax.tree.leaves(
            jax.eval_shape(lambda: init_resnet(jax.random.PRNGKey(0), self)))
        return sum(int(jnp.prod(jnp.asarray(l.shape))) for l in leaves)


RESNET_CONFIGS: Dict[str, ResNetConfig] = {
    "tiny": ResNetConfig(channels=(8, 16), depths=(1, 1), groups=4,
                         dtype=jnp.float32),
    "resnet18": ResNetConfig(),
    "resnet34": ResNetConfig(depths=(3, 4, 6, 3)),
}


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * (2.0 / fan_in) ** 0.5).astype(dtype)


def init_resnet(key, cfg: ResNetConfig, in_channels: int = 3):
    keys = iter(jax.random.split(key, 4 + 4 * sum(cfg.depths)))
    params: Dict[str, Any] = {
        "stem": _conv_init(next(keys), cfg.stem_kernel, cfg.stem_kernel,
                           in_channels, cfg.channels[0], cfg.dtype),
        "stem_scale": jnp.ones(cfg.channels[0], cfg.dtype),
        "stages": [],
    }
    cin = cfg.channels[0]
    for stage, (cout, depth) in enumerate(zip(cfg.channels, cfg.depths)):
        blocks: List[Dict[str, Any]] = []
        for b in range(depth):
            block = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout, cfg.dtype),
                "scale1": jnp.ones(cout, cfg.dtype),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout, cfg.dtype),
                "scale2": jnp.ones(cout, cfg.dtype),
            }
            if cin != cout:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout,
                                           cfg.dtype)
            blocks.append(block)
            cin = cout
        params["stages"].append(blocks)
    params["head"] = (jax.random.normal(
        next(keys), (cfg.channels[-1], cfg.num_classes), jnp.float32)
        * (cfg.channels[-1] ** -0.5)).astype(cfg.dtype)
    params["head_b"] = jnp.zeros(cfg.num_classes, cfg.dtype)
    return params


def _group_norm(x, scale, groups: int):
    # f32 statistics regardless of activation dtype
    B, H, W, C = x.shape
    g = min(groups, C)
    xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    return (xf.reshape(B, H, W, C) * scale.astype(jnp.float32)).astype(
        x.dtype)


def _conv(x, w, stride: int = 1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def resnet_forward(params, images, cfg: ResNetConfig):
    """images (B, H, W, C) float in [0,1] -> logits (B, num_classes) f32."""
    x = _conv(images.astype(cfg.dtype), params["stem"])
    x = jax.nn.relu(_group_norm(x, params["stem_scale"], cfg.groups))
    for stage, blocks in enumerate(params["stages"]):
        for b, block in enumerate(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            h = _conv(x, block["conv1"], stride)
            h = jax.nn.relu(_group_norm(h, block["scale1"], cfg.groups))
            h = _conv(h, block["conv2"])
            h = _group_norm(h, block["scale2"], cfg.groups)
            shortcut = x
            if "proj" in block:
                shortcut = _conv(x, block["proj"], stride)
            elif stride != 1:
                shortcut = x[:, ::stride, ::stride, :]
            x = jax.nn.relu(h + shortcut)
    x = x.mean(axis=(1, 2))  # global average pool
    logits = jnp.einsum("bc,cn->bn", x.astype(cfg.dtype), params["head"],
                        preferred_element_type=jnp.float32)
    return logits + params["head_b"].astype(jnp.float32)


def image_loss(params, batch, cfg: ResNetConfig, **_):
    """Cross-entropy over {"images": (B,H,W,C), "labels": (B,)}."""
    logits = resnet_forward(params, batch["images"], cfg)
    labels = batch["labels"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - tgt).mean()


def resnet_param_axes(params):
    """Logical axes: everything replicated (vision models this size are
    pure data-parallel; batch sharding comes from the train step)."""
    return jax.tree.map(lambda _: (), params)
