"""CLIP: contrastive image-text pretraining, TPU-first.

One of the BASELINE configs ("ViT-L / CLIP multimodal — Ray Data image
pipeline -> TPU"). Two towers — a ViT image encoder (patchify = one
reshaped matmul, so even embedding rides the MXU) and a pre-norm
transformer text encoder — meet in a shared embedding space under the
symmetric InfoNCE loss with a learnable temperature (Radford et al.
2021 defines the objective; the implementation here is a fresh jax
program sharing this repo's ops and logical-axis sharding rules).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops import rms_norm
from ..ops.attention import blockwise_attention


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    # vision tower
    image_size: int = 224
    patch: int = 16
    v_dim: int = 768
    v_layers: int = 12
    v_heads: int = 12
    # text tower
    vocab: int = 49408
    max_text: int = 77
    t_dim: int = 512
    t_layers: int = 12
    t_heads: int = 8
    # shared space
    embed_dim: int = 512
    mlp_ratio: int = 4
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    def n_params(self) -> int:
        def tower(dim, layers):
            attn = 4 * dim * dim
            mlp = 2 * dim * dim * self.mlp_ratio
            return layers * (attn + mlp + 2 * dim)
        v = (self.patch ** 2 * 3 * self.v_dim          # patch embed
             + (self.n_patches + 1) * self.v_dim       # pos + cls
             + tower(self.v_dim, self.v_layers)
             + self.v_dim * self.embed_dim)
        t = (self.vocab * self.t_dim
             + self.max_text * self.t_dim
             + tower(self.t_dim, self.t_layers)
             + self.t_dim * self.embed_dim)
        return v + t + 1


CLIP_CONFIGS: Dict[str, CLIPConfig] = {
    "tiny": CLIPConfig(image_size=32, patch=8, v_dim=64, v_layers=2,
                       v_heads=4, vocab=256, max_text=16, t_dim=64,
                       t_layers=2, t_heads=4, embed_dim=32,
                       dtype=jnp.float32, remat=False),
    # ViT-B/16-class two-tower (the classic CLIP-B recipe)
    "vit_b16": CLIPConfig(),
}


def _tower_axes(prefix):
    return {
        "attn_norm": ("layers", prefix),
        "wqkv": ("layers", prefix, "heads_qkv"),
        "wo": ("layers", "heads_qkv", prefix),
        "mlp_norm": ("layers", prefix),
        "w_up": ("layers", prefix, "mlp"),
        "w_down": ("layers", "mlp", prefix),
    }


def clip_param_axes(cfg: CLIPConfig):
    return {
        "vision": {
            "patch_embed": (None, "embed"),
            "cls": (None, None, "embed"),
            "pos": (None, "embed"),
            "tower": _tower_axes("embed"),
            "norm": ("embed",),
            "proj": ("embed", "clip"),
        },
        "text": {
            "embed": ("vocab", "embed"),
            "pos": (None, "embed"),
            "tower": _tower_axes("embed"),
            "norm": ("embed",),
            "proj": ("embed", "clip"),
        },
        "logit_scale": (),
    }


def _init_tower(key, dim: int, layers: int, mlp: int, dtype):
    ks = jax.random.split(key, 4)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "attn_norm": jnp.ones((layers, dim), dtype),
        "wqkv": w(ks[0], (layers, dim, 3 * dim), dim),
        "wo": w(ks[1], (layers, dim, dim), dim),
        "mlp_norm": jnp.ones((layers, dim), dtype),
        "w_up": w(ks[2], (layers, dim, mlp), dim),
        "w_down": w(ks[3], (layers, mlp, dim), mlp),
    }


def init_clip(key, cfg: CLIPConfig):
    ks = jax.random.split(key, 8)
    pd = cfg.patch * cfg.patch * 3

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        "vision": {
            "patch_embed": w(ks[0], (pd, cfg.v_dim), pd),
            "cls": jnp.zeros((1, 1, cfg.v_dim), cfg.dtype),
            "pos": w(ks[1], (cfg.n_patches + 1, cfg.v_dim), cfg.v_dim),
            "tower": _init_tower(ks[2], cfg.v_dim, cfg.v_layers,
                                 cfg.v_dim * cfg.mlp_ratio, cfg.dtype),
            "norm": jnp.ones((cfg.v_dim,), cfg.dtype),
            "proj": w(ks[3], (cfg.v_dim, cfg.embed_dim), cfg.v_dim),
        },
        "text": {
            "embed": w(ks[4], (cfg.vocab, cfg.t_dim), cfg.t_dim),
            "pos": w(ks[5], (cfg.max_text, cfg.t_dim), cfg.t_dim),
            "tower": _init_tower(ks[6], cfg.t_dim, cfg.t_layers,
                                 cfg.t_dim * cfg.mlp_ratio, cfg.dtype),
            "norm": jnp.ones((cfg.t_dim,), cfg.dtype),
            "proj": w(ks[7], (cfg.t_dim, cfg.embed_dim), cfg.t_dim),
        },
        # exp(logit_scale) starts at 1/0.07, the CLIP-standard init
        "logit_scale": jnp.asarray(jnp.log(1.0 / 0.07), jnp.float32),
    }


def _run_tower(x, tower, heads: int, cfg: CLIPConfig, causal: bool):
    head_dim = x.shape[-1] // heads

    def layer(x, lp):
        B_, S, d = x.shape
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        qkv = (h @ lp["wqkv"]).reshape(B_, S, 3, heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = blockwise_attention(q, k, v, causal=causal)
        x = x + (att.reshape(B_, S, d) @ lp["wo"]).astype(x.dtype)
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + (jax.nn.gelu(h @ lp["w_up"]) @ lp["w_down"]).astype(x.dtype)
        return x, None

    body = jax.checkpoint(layer) if cfg.remat else layer
    x, _ = jax.lax.scan(body, x, tower)
    return x


def encode_image(params, images, cfg: CLIPConfig):
    """images: (B, H, W, 3) -> L2-normalized (B, embed_dim)."""
    vp = params["vision"]
    B_ = images.shape[0]
    p, g = cfg.patch, cfg.image_size // cfg.patch
    # patchify as a reshape: (B, g, p, g, p, 3) -> (B, g*g, p*p*3)
    x = images.astype(cfg.dtype).reshape(B_, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B_, g * g, p * p * 3)
    x = x @ vp["patch_embed"]
    cls = jnp.broadcast_to(vp["cls"], (B_, 1, cfg.v_dim))
    x = jnp.concatenate([cls, x], axis=1) + vp["pos"][None]
    x = _run_tower(x, vp["tower"], cfg.v_heads, cfg, causal=False)
    pooled = rms_norm(x[:, 0], vp["norm"], cfg.norm_eps)
    emb = (pooled @ vp["proj"]).astype(jnp.float32)
    return emb / (jnp.linalg.norm(emb, axis=-1, keepdims=True) + 1e-8)


def encode_text(params, tokens, cfg: CLIPConfig):
    """tokens: (B, T) int32, 0 = pad -> L2-normalized (B, embed_dim).
    Pooling reads the LAST non-pad position (causal tower), CLIP's
    EOT-pooling shape."""
    tp = params["text"]
    T = tokens.shape[1]
    x = tp["embed"][tokens].astype(cfg.dtype) + tp["pos"][None, :T]
    x = _run_tower(x, tp["tower"], cfg.t_heads, cfg, causal=True)
    lengths = jnp.maximum((tokens != 0).sum(axis=1) - 1, 0)
    pooled = jnp.take_along_axis(
        x, lengths[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    pooled = rms_norm(pooled, tp["norm"], cfg.norm_eps)
    emb = (pooled @ tp["proj"]).astype(jnp.float32)
    return emb / (jnp.linalg.norm(emb, axis=-1, keepdims=True) + 1e-8)


def clip_outputs(params, batch, cfg: CLIPConfig):
    """Symmetric InfoNCE over the batch's (image, text) pairs, with
    diagnostics."""
    img = encode_image(params, batch["images"], cfg)
    txt = encode_text(params, batch["tokens"], cfg)
    scale = jnp.exp(jnp.clip(params["logit_scale"], -10.0, jnp.log(100.0)))
    logits = img @ txt.T * scale
    labels = jnp.arange(logits.shape[0])
    li = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=1),
                              labels[:, None], axis=1).mean()
    lt = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=0),
                              labels[None, :], axis=0).mean()
    loss = 0.5 * (li + lt)
    acc = (logits.argmax(axis=1) == labels).mean()
    return {"loss": loss, "contrastive_acc": acc, "logit_scale": scale}


def clip_loss(params, batch, cfg: CLIPConfig, **_):
    """Scalar loss — the make_train_step contract."""
    return clip_outputs(params, batch, cfg)["loss"]
