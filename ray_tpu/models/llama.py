"""Llama-3 family: functional jax transformer, TPU-first.

Design choices for the TPU/XLA compilation model:
  * **scan over layers** — one compiled layer body, stacked params with a
    leading "layers" axis: compile time stays flat as depth grows.
  * **remat per layer** (``jax.checkpoint``) — trades FLOPs for HBM,
    standard recipe for long-sequence training.
  * **logical axis names** on every param; the rules table
    (ray_tpu.parallel.sharding) maps them onto the dp/fsdp/tp/sp mesh, so
    FSDP/TP/SP layouts need no model edits (GSPMD inserts collectives).
  * **bf16 params/activations, f32 accumulation** in norms/softmax/loss.
  * attention dispatches to the Pallas flash kernel on TPU, ring
    attention over the "sp" axis when sequence-parallel is active.

The reference has no native model code (tensors delegated to torch/vLLM
— SURVEY §2.3); this file is the BASELINE "Llama-3 8B" config substrate.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from ..util.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import apply_rotary, attention, ring_attention, rms_norm, rope_frequencies
from ..parallel.sharding import DEFAULT_RULES, with_sharding_constraint_logical


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    mlp_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Mixture-of-Experts: n_experts > 0 replaces the dense MLP with a
    # top-k routed expert MLP (experts sharded over the "ep" mesh axis)
    n_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 2.0
    aux_loss_coef: float = 0.01
    # "full": recompute everything (max HBM savings, ~1/3 extra FLOPs);
    # "dots": save matmul outputs, recompute elementwise only — the right
    # trade when HBM fits it (ref: jax checkpoint_policies)
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def n_params(self) -> int:
        d, L = self.dim, self.n_layers
        attn = d * self.n_heads * self.head_dim + 2 * d * self.n_kv_heads * self.head_dim \
            + self.n_heads * self.head_dim * d
        mlp = 3 * d * self.mlp_dim
        if self.n_experts:
            mlp = self.n_experts * mlp + d * self.n_experts  # experts+router
        return self.vocab * d * 2 + L * (attn + mlp + 2 * d) + d


LLAMA_CONFIGS: Dict[str, LlamaConfig] = {
    # test-size model: fits CPU tests, exercises GQA (4 q heads, 2 kv).
    "tiny": LlamaConfig(vocab=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, mlp_dim=128, max_seq=256,
                        dtype=jnp.float32, remat=False),
    # ~420M: single-chip bench size. head_dim=128 (8 heads on dim 1024) —
    # the MXU-native head width the flash kernels tile on; identical param
    # count to a 16-head/64-dim layout, far faster to train.
    "400m": LlamaConfig(vocab=32768, dim=1024, n_layers=24, n_heads=8,
                        n_kv_heads=4, mlp_dim=2816, max_seq=2048,
                        remat_policy="dots"),
    "1b": LlamaConfig(vocab=128256, dim=2048, n_layers=16, n_heads=16,
                      n_kv_heads=8, mlp_dim=8192, max_seq=8192),
    "8b": LlamaConfig(),  # Llama-3-8B (BASELINE config #1)
    "70b": LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                       mlp_dim=28672),
}


# ---------------------------------------------------------------------------
# Params: nested dict, layer params stacked on a leading "layers" axis.
# ---------------------------------------------------------------------------


def param_logical_axes(cfg: LlamaConfig):
    """Pytree of logical-axis tuples mirroring init_params' structure."""
    if cfg.n_experts:
        mlp_axes = {
            "router": ("layers", "embed", "expert"),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        }
    else:
        mlp_axes = {
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        }
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "embed"),
            **mlp_axes,
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(key, cfg: LlamaConfig):
    """Scaled-normal init (1/sqrt(fan_in)); bf16 storage."""
    L, d, hd = cfg.n_layers, cfg.dim, cfg.head_dim
    h, hkv, m = cfg.n_heads, cfg.n_kv_heads, cfg.mlp_dim
    ks = jax.random.split(key, 9)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    if cfg.n_experts:
        E = cfg.n_experts
        kr = jax.random.split(ks[5], 4)
        mlp_params = {
            # router stays genuinely f32 (no bf16 round trip): routing
            # decisions are precision-sensitive
            "router": jax.random.normal(kr[0], (L, d, E), jnp.float32)
            * (d ** -0.5),
            "w_gate": norm(kr[1], (L, E, d, m), d),
            "w_up": norm(kr[2], (L, E, d, m), d),
            "w_down": norm(kr[3], (L, E, m, d), m),
        }
    else:
        mlp_params = {
            "w_gate": norm(ks[5], (L, d, m), d),
            "w_up": norm(ks[6], (L, d, m), d),
            "w_down": norm(ks[7], (L, m, d), m),
        }
    return {
        "embed": norm(ks[0], (cfg.vocab, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": norm(ks[1], (L, d, h, hd), d),
            "wk": norm(ks[2], (L, d, hkv, hd), d),
            "wv": norm(ks[3], (L, d, hkv, hd), d),
            "wo": norm(ks[4], (L, h, hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            **mlp_params,
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": norm(ks[8], (d, cfg.vocab), d),
    }


# ---------------------------------------------------------------------------
# Forward.
# ---------------------------------------------------------------------------


def _attn(x, lp, cfg: LlamaConfig, cos, sin, mesh: Optional[Mesh], rules):
    b, s, d = x.shape
    q = jnp.einsum("bsd,dhk->bshk", x, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", x, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"])
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        # Sequence parallel: tokens sharded over "sp"; exact ring attention
        # rotates kv shards over single-hop ICI neighbours.
        spec = P(("dp", "fsdp"), "sp", "tp", None)
        out = shard_map(
            partial(ring_attention, axis="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)
    else:
        out = attention(q, k, v, causal=True)
    out = jnp.einsum("bshk,hkd->bsd", out, lp["wo"])
    return out


def _mlp(x, lp, cfg: LlamaConfig, csl):
    if cfg.n_experts:
        from ..ops.moe import moe_mlp

        out, aux = moe_mlp(
            x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.top_k, capacity_factor=cfg.capacity_factor, csl=csl)
        return out, aux
    # SwiGLU; gate/up fuse into one pass over x in XLA.
    g = jnp.einsum("bsd,dm->bsm", x, lp["w_gate"])
    u = jnp.einsum("bsd,dm->bsm", x, lp["w_up"])
    out = jnp.einsum("bsm,md->bsd", jax.nn.silu(g) * u, lp["w_down"])
    return out, jnp.zeros((), jnp.float32)


def forward(params, tokens, cfg: LlamaConfig, *,
            mesh: Optional[Mesh] = None, rules=DEFAULT_RULES,
            return_aux: bool = False):
    """tokens (B, S) int32 → logits (B, S, vocab) in f32.

    ``return_aux``: also return the summed MoE load-balancing loss."""
    csl = partial(with_sharding_constraint_logical, rules=rules, mesh=mesh)
    cos, sin = rope_frequencies(cfg.head_dim, tokens.shape[1],
                                cfg.rope_theta, dtype=jnp.float32)

    # Embedding lookup, transpose-stable: the stored table is
    # (vocab→tp, embed→fsdp)-sharded while activations are batch-sharded
    # over (dp, fsdp); gathering straight from the stored layout makes
    # SPMD move data between the fsdp and dp mesh dims — a device-order
    # transposition it can only do by full rematerialization (replicate
    # + repartition), in the forward AND its jvp transpose. Dropping the
    # table's embed-dim sharding first keeps the gather's vocab dim on
    # tp (masked gather + psum, the efficient partitioned path) and the
    # output reshard to batch is then a local slice.
    tbl = csl(params["embed"], ("vocab", None))
    x = jnp.take(tbl, tokens, axis=0)
    x = csl(x, ("batch", "seq", "embed"))

    def layer(x, lp):
        h = x + _attn(rms_norm(x, lp["attn_norm"], cfg.norm_eps),
                      lp, cfg, cos, sin, mesh, rules)
        h = csl(h, ("batch", "seq", "embed"))
        mlp_out, aux = _mlp(rms_norm(h, lp["mlp_norm"], cfg.norm_eps),
                            lp, cfg, csl)
        out = h + mlp_out
        return csl(out, ("batch", "seq", "embed")), aux

    if cfg.remat and cfg.remat_policy == "dots":
        body = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif cfg.remat:
        body = jax.checkpoint(layer)
    else:
        body = layer
    x, aux_losses = jax.lax.scan(body, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # bf16 operands on the MXU with f32 accumulation — an f32 lm_head
    # matmul runs at half peak and is ~10% of model FLOPs at 32k vocab
    logits = jnp.einsum("bsd,dv->bsv", x.astype(cfg.dtype),
                        params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logits = csl(logits, ("batch", "seq", "vocab"))
    if return_aux:
        return logits, jnp.sum(aux_losses)
    return logits


def lm_loss(params, batch, cfg: LlamaConfig, *,
            mesh: Optional[Mesh] = None, rules=DEFAULT_RULES,
            z_loss: float = 1e-4):
    """Next-token cross-entropy (f32) with optional z-loss regularizer.

    batch: {"tokens": (B, S) int32, "mask": optional (B, S) 0/1 valid}.
    Targets are tokens shifted left; the final position is dropped.
    """
    tokens = batch["tokens"]
    logits, aux = forward(params, tokens, cfg, mesh=mesh, rules=rules,
                          return_aux=True)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, targets[..., None],
                                    axis=-1)[..., 0]
    nll = logz - tgt_logit
    if z_loss:
        nll = nll + z_loss * jnp.square(logz)
    mask = batch.get("mask")
    mask = jnp.ones_like(nll) if mask is None else mask[:, 1:].astype(nll.dtype)
    loss = (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    if cfg.n_experts:
        loss = loss + cfg.aux_loss_coef * aux
    return loss
