"""Mamba-2 family: selective state-space LM, TPU-first.

One of the BASELINE configs ("Mamba-2 / Jamba hybrid — state-space ops").
The core op is ops/ssd.py's chunked SSD — the state-space-duality form
whose FLOPs are einsums the MXU tiles natively; the per-chunk scan is
the only sequential dependency (seq/chunk steps instead of seq).

Block layout follows Mamba-2's parallel projection: one in_proj emits
[z | x | B | C | dt], a short causal depthwise conv warms x/B/C locally,
SSD mixes along the sequence, the gate z modulates, out_proj returns to
the residual stream. Params carry logical axes so the same
dp/fsdp/tp/sp rule table shards this family too.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops import rms_norm
from ..ops.ssd import ssd_chunked


@dataclasses.dataclass(frozen=True)
class MambaConfig:
    vocab: int = 32768
    dim: int = 768
    n_layers: int = 24
    state_dim: int = 64
    head_dim: int = 64
    expand: int = 2
    conv_width: int = 4
    chunk: int = 64
    dt_min: float = 0.001
    dt_max: float = 0.1
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Jamba hybrid (BASELINE "Mamba-2 / Jamba hybrid"): every
    # `attn_period`-th layer group ends with ONE attention layer —
    # n_layers must divide by attn_period. 0 = pure Mamba. Attention
    # reuses the llama-family GQA + rotary ops; the scan runs over
    # PERIODS so the compiled body stays one period regardless of depth.
    attn_period: int = 0
    attn_heads: int = 8
    attn_kv_heads: int = 4
    rope_theta: float = 10000.0

    def __post_init__(self):
        if self.attn_period:
            if self.n_layers % self.attn_period:
                raise ValueError(
                    f"n_layers={self.n_layers} must divide by "
                    f"attn_period={self.attn_period}")
            if self.dim % self.attn_heads:
                raise ValueError(
                    f"dim={self.dim} must divide by "
                    f"attn_heads={self.attn_heads}")
            if self.attn_heads % self.attn_kv_heads:
                raise ValueError(
                    f"attn_heads={self.attn_heads} must divide by "
                    f"attn_kv_heads={self.attn_kv_heads}")

    @property
    def inner(self) -> int:
        return self.expand * self.dim

    @property
    def n_heads(self) -> int:
        return self.inner // self.head_dim

    @property
    def n_attn_layers(self) -> int:
        return self.n_layers // self.attn_period if self.attn_period else 0

    @property
    def n_mamba_layers(self) -> int:
        return self.n_layers - self.n_attn_layers

    def n_params(self) -> int:
        d, di, H = self.dim, self.inner, self.n_heads
        # in_proj emits z(di) + x(di) + B(N) + C(N) + dt(H) per token
        proj_in = d * (2 * di + 2 * self.state_dim + H)
        conv = self.conv_width * (di + 2 * self.state_dim)
        per_layer = proj_in + conv + di * d + 3 * H + d
        hd = d // self.attn_heads if self.attn_period else 0
        per_attn = (d * (self.attn_heads + 2 * self.attn_kv_heads) * hd
                    + self.attn_heads * hd * d + d)
        return (self.vocab * d * 2 + self.n_mamba_layers * per_layer
                + self.n_attn_layers * per_attn + d)


MAMBA_CONFIGS: Dict[str, MambaConfig] = {
    "tiny": MambaConfig(vocab=256, dim=64, n_layers=2, state_dim=16,
                        head_dim=32, chunk=16, dtype=jnp.float32,
                        remat=False),
    # ~130M class, single-chip bench size
    "130m": MambaConfig(vocab=32768, dim=768, n_layers=24),
    "1b": MambaConfig(vocab=32768, dim=2048, n_layers=48),
    # Jamba-style hybrid: 3 mamba layers then 1 attention layer per period
    "jamba_tiny": MambaConfig(vocab=256, dim=64, n_layers=4, state_dim=16,
                              head_dim=32, chunk=16, attn_period=4,
                              attn_heads=4, attn_kv_heads=2,
                              dtype=jnp.float32, remat=False),
    "jamba_350m": MambaConfig(vocab=32768, dim=1024, n_layers=32,
                              attn_period=4, attn_heads=8,
                              attn_kv_heads=4),
}


def mamba_param_axes(cfg: MambaConfig):
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "norm": ("layers", "embed"),
            "w_in": ("layers", "embed", "mlp"),
            "conv": ("layers", "conv", "mlp"),
            "dt_bias": ("layers", "heads"),
            "A_log": ("layers", "heads"),
            "Dp": ("layers", "heads"),
            "w_out": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
        **({"attn_layers": {
            "norm": ("layers", "embed"),
            "wqkv": ("layers", "embed", "heads_qkv"),
            "wo": ("layers", "heads_qkv", "embed"),
        }} if cfg.attn_period else {}),
    }


def init_mamba(key, cfg: MambaConfig):
    d, di, N, H = cfg.dim, cfg.inner, cfg.state_dim, cfg.n_heads
    L = cfg.n_mamba_layers
    proj_width = 2 * di + 2 * N + H
    ks = jax.random.split(key, 9)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    # dt bias: softplus(bias) spans [dt_min, dt_max] log-uniformly;
    # the decay magnitude |A| in [1, 16) draws INDEPENDENTLY (coupling
    # them would make fast-timestep heads systematically fast-decaying)
    u = jax.random.uniform(ks[3], (L, H), jnp.float32)
    ua = jax.random.uniform(ks[6], (L, H), jnp.float32)
    dt_init = jnp.exp(u * (jnp.log(cfg.dt_max) - jnp.log(cfg.dt_min))
                      + jnp.log(cfg.dt_min))
    dt_bias = dt_init + jnp.log(-jnp.expm1(-dt_init))  # inv softplus
    return {
        "embed": norm_init(ks[0], (cfg.vocab, d), d),
        "layers": {
            "norm": jnp.ones((L, d), cfg.dtype),
            "w_in": norm_init(ks[1], (L, d, proj_width), d),
            "conv": (jax.random.normal(
                ks[2], (L, cfg.conv_width, di + 2 * N), jnp.float32)
                * (cfg.conv_width ** -0.5)).astype(cfg.dtype),
            "dt_bias": dt_bias,
            # A in [-16, -1]: exp(A_log) gives the magnitude
            "A_log": jnp.log(1.0 + ua * 15.0),
            "Dp": jnp.ones((L, H), jnp.float32),
            "w_out": norm_init(ks[4], (L, di, d), di),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": norm_init(ks[5], (d, cfg.vocab), d),
        **({"attn_layers": {
            "norm": jnp.ones((cfg.n_attn_layers, d), cfg.dtype),
            "wqkv": norm_init(
                ks[7], (cfg.n_attn_layers, d,
                        (cfg.attn_heads + 2 * cfg.attn_kv_heads)
                        * (d // cfg.attn_heads)), d),
            "wo": norm_init(ks[8], (cfg.n_attn_layers, d, d), d),
        }} if cfg.attn_period else {}),
    }


def _causal_depthwise_conv(x, w):
    """x: (B, S, C), w: (K, C) — causal depthwise conv along S."""
    K = w.shape[0]
    pad = jnp.pad(x, ((0, 0), (K - 1, 0), (0, 0)))
    # unrolled taps: K is 4 — cheaper to fuse than to dispatch conv
    out = jnp.zeros_like(x)
    for k in range(K):
        out = out + pad[:, k:k + x.shape[1], :] * w[k][None, None, :]
    return out


def _block(x, lp, cfg: MambaConfig):
    B_, S, d = x.shape
    di, N, H, P = cfg.inner, cfg.state_dim, cfg.n_heads, cfg.head_dim
    h = rms_norm(x, lp["norm"], cfg.norm_eps)
    proj = h @ lp["w_in"]
    z, xs, Bc, Cc, dt_raw = jnp.split(
        proj, [di, 2 * di, 2 * di + N, 2 * di + 2 * N], axis=-1)
    # local conv over the SSD operands (x, B, C together, mamba-2 style)
    conv_in = jnp.concatenate([xs, Bc, Cc], axis=-1)
    conv_out = jax.nn.silu(_causal_depthwise_conv(conv_in, lp["conv"]))
    xs, Bc, Cc = jnp.split(conv_out, [di, di + N], axis=-1)
    # NOTE: no explicit sharding constraint here — under dp x fsdp the
    # batch-over-(dp,fsdp) activation spec conflicts with the
    # fsdp-sharded w_in/w_out specs inside the scan body and forces an
    # "Involuntary full rematerialization" reshard in SPMD (observed on
    # the 8-device mesh, VERDICT r4 weak #2); propagation from the
    # sharded batch input yields the same layout without the conflict.
    xs = xs.reshape(B_, S, H, P)
    dt = jax.nn.softplus(
        dt_raw.astype(jnp.float32)
        + lp["dt_bias"].astype(jnp.float32)[None, None, :])
    A = -jnp.exp(lp["A_log"].astype(jnp.float32))
    # B/C shared across heads (single group): the (B,S,1,N) shape lets
    # ssd_chunked compute the shared contractions once and broadcast
    y = ssd_chunked(xs, dt, A, Bc[:, :, None, :], Cc[:, :, None, :],
                    lp["Dp"], cfg.chunk)
    y = y.reshape(B_, S, di) * jax.nn.silu(z)
    return x + (y @ lp["w_out"]).astype(x.dtype)


def _attn_block(x, ap, cfg: MambaConfig, cos, sin):
    """One GQA attention layer (the Jamba hybrid's periodic layer),
    sharing the llama-family attention/rotary ops."""
    from ..ops import apply_rotary
    from ..ops.attention import attention

    B_, S, d = x.shape
    hN, kvN = cfg.attn_heads, cfg.attn_kv_heads
    hd = d // hN
    h = rms_norm(x, ap["norm"], cfg.norm_eps)
    qkv = h @ ap["wqkv"]
    q, k, v = jnp.split(qkv, [hN * hd, (hN + kvN) * hd], axis=-1)
    q = apply_rotary(q.reshape(B_, S, hN, hd), cos, sin)
    k = apply_rotary(k.reshape(B_, S, kvN, hd), cos, sin)
    v = v.reshape(B_, S, kvN, hd)
    att = attention(q, k, v, causal=True)
    return x + (att.reshape(B_, S, hN * hd) @ ap["wo"]).astype(x.dtype)


def mamba_forward(params, tokens, cfg: MambaConfig, *,
                  mesh: Optional[Any] = None, rules=None):
    # ``mesh``/``rules`` are accepted for signature parity with the other
    # model families but are deliberate NO-OPS: explicit activation
    # constraints here conflicted with the fsdp-sharded param specs and
    # forced SPMD full-rematerialization (see _block note); sharding
    # flows from the place_batch-sharded tokens + shard_pytree'd params.
    # the chunked SSD needs seq % chunk == 0: right-pad with zeros (a
    # causal model's outputs at real positions can't see the pad tail)
    S = tokens.shape[1]
    pad = (-S) % cfg.chunk
    if pad:
        tokens = jnp.pad(tokens, ((0, 0), (0, pad)))
    # batch sharding flows from the (place_batch-sharded) tokens input;
    # see the note in _block for why there is no explicit constraint
    x = params["embed"][tokens].astype(cfg.dtype)

    if cfg.attn_period:
        # Jamba hybrid: scan over PERIODS of (attn_period-1) mamba
        # layers + 1 attention layer — the compiled body is one period
        # regardless of depth
        from ..ops import rope_frequencies

        per = cfg.attn_period - 1
        n_per = cfg.n_attn_layers
        cos, sin = rope_frequencies(cfg.dim // cfg.attn_heads,
                                    tokens.shape[1], cfg.rope_theta)
        mamba_periods = jax.tree.map(
            lambda a: a.reshape((n_per, per) + a.shape[1:]),
            params["layers"])

        def period(x, pp):
            mp, ap = pp

            def inner(x, lp):
                return _block(x, lp, cfg), None

            x, _ = jax.lax.scan(inner, x, mp)
            return _attn_block(x, ap, cfg, cos, sin), None

        body = jax.checkpoint(period) if cfg.remat else period
        x, _ = jax.lax.scan(body, x, (mamba_periods,
                                      params["attn_layers"]))
    else:
        def layer(x, lp):
            return _block(x, lp, cfg), None

        body = jax.checkpoint(layer) if cfg.remat else layer
        x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if pad:
        x = x[:, :S]
    return (x @ params["lm_head"]).astype(jnp.float32)


def mamba_lm_loss(params, batch, cfg: MambaConfig, *,
                  mesh: Optional[Any] = None, rules=None):
    """Scalar next-token loss — the make_train_step contract."""
    tokens = batch["tokens"]
    logits = mamba_forward(params, tokens[:, :-1], cfg,
                           mesh=mesh, rules=rules)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
