"""ray_tpu.models: TPU-native model families.

The reference ships no models of its own (it orchestrates torch models;
its LLM path wraps vLLM — ref: python/ray/llm/_internal/serve/deployments/
llm/vllm/vllm_models.py). Here the models are first-class jax programs
with logical-axis sharding so the same definition runs dp/fsdp/tp/sp
layouts by rule swap (BASELINE configs: Llama-3 8B/70B, Mixtral MoE,
ViT/CLIP, Mamba).
"""

from .llama import (
    LlamaConfig,
    LLAMA_CONFIGS,
    init_params,
    param_logical_axes,
    forward,
    lm_loss,
)
from .vision import (
    RESNET_CONFIGS,
    ResNetConfig,
    image_loss,
    init_resnet,
    resnet_forward,
    resnet_param_axes,
)
from .mamba import (
    MAMBA_CONFIGS,
    MambaConfig,
    init_mamba,
    mamba_forward,
    mamba_lm_loss,
    mamba_param_axes,
)
from .clip import (
    CLIP_CONFIGS,
    CLIPConfig,
    clip_loss,
    clip_param_axes,
    encode_image,
    encode_text,
    init_clip,
)

__all__ = [
    "LlamaConfig", "LLAMA_CONFIGS", "init_params", "param_logical_axes",
    "forward", "lm_loss",
    "ResNetConfig", "RESNET_CONFIGS", "init_resnet", "resnet_forward",
    "image_loss", "resnet_param_axes",
    "MambaConfig", "MAMBA_CONFIGS", "init_mamba", "mamba_forward",
    "mamba_lm_loss", "mamba_param_axes",
    "CLIPConfig", "CLIP_CONFIGS", "init_clip", "encode_image",
    "encode_text", "clip_loss", "clip_param_axes",
]
