"""Training run configuration (ref: python/ray/air/config.py —
ScalingConfig/RunConfig/CheckpointConfig/FailureConfig; train/v2/api/config.py).

TPU deltas: ``resources_per_worker`` defaults to one host's worth of chips
when ``use_tpu`` is set, and workers are gang-placed with STRICT_SPREAD so
each host of a slice gets exactly one controller process (SPMD
multi-controller model, SURVEY §7.1)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """Shape of the worker gang."""

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    # PG strategy for the gang; STRICT_SPREAD = one worker per host (the TPU
    # slice model), PACK = colocate when possible (CPU tests, small jobs)
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"CPU": 1.0, "TPU": 4.0}  # one v5p host's chips
        return {"CPU": 1.0}


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None          # None = keep all


@dataclass
class FailureConfig:
    max_failures: int = 0                      # gang restarts allowed


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None         # default: /tmp/ray_tpu_results
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
