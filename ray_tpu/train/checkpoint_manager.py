"""Checkpoint registration + retention (ref: train/v2/_internal/execution/
checkpoint/checkpoint_manager.py — register reported checkpoints under the
run's storage path, keep the top-k most recent, expose the latest for
restore)."""

from __future__ import annotations

import os
import shutil

from typing import List, Optional

from ._checkpoint import Checkpoint
from .config import CheckpointConfig


class CheckpointManager:
    def __init__(self, storage_dir: str, config: CheckpointConfig):
        self.storage_dir = storage_dir
        self.config = config
        self._registered: List[str] = []   # oldest → newest, persisted dirs
        os.makedirs(storage_dir, exist_ok=True)
        # resume support: pre-existing checkpoint dirs from a previous run.
        # In-progress staging dirs (crash mid-copy) are cleaned, never
        # registered — only atomically-renamed final dirs count.
        for name in sorted(os.listdir(storage_dir)):
            path = os.path.join(storage_dir, name)
            if name.startswith("_staging_"):
                shutil.rmtree(path, ignore_errors=True)
            elif name.startswith("checkpoint_"):
                self._registered.append(path)

    def max_step(self) -> int:
        """Highest step already persisted (resume must continue past it)."""
        best = 0
        for path in self._registered:
            name = os.path.basename(path)
            try:
                best = max(best, int(name.split("_")[-1]))
            except ValueError:
                pass
        return best

    def register(self, source_path: str, step: int) -> Checkpoint:
        """Persist a worker-reported checkpoint directory into storage.
        Copy lands in a staging dir and is renamed into place, so a crash
        mid-copy can never leave a half checkpoint that resume would trust."""
        target = os.path.join(self.storage_dir, f"checkpoint_{step:06d}")
        if os.path.abspath(source_path) != target:
            staging = os.path.join(self.storage_dir, f"_staging_{step:06d}")
            shutil.rmtree(staging, ignore_errors=True)
            shutil.copytree(source_path, staging)
            if os.path.exists(target):
                shutil.rmtree(target)
            os.rename(staging, target)
        if target not in self._registered:
            self._registered.append(target)
        self._apply_retention()
        return Checkpoint(target)

    def register_bytes(self, blob: bytes, step: int) -> Checkpoint:
        """Persist a checkpoint shipped as a tar blob (cross-node path: the
        worker's filesystem is not ours)."""
        from ._checkpoint import unpack_blob

        staging = os.path.join(self.storage_dir, f"_staging_{step:06d}")
        shutil.rmtree(staging, ignore_errors=True)
        unpack_blob(blob, staging)
        target = os.path.join(self.storage_dir, f"checkpoint_{step:06d}")
        if os.path.exists(target):
            shutil.rmtree(target)
        os.rename(staging, target)
        if target not in self._registered:
            self._registered.append(target)
        self._apply_retention()
        return Checkpoint(target)

    def _apply_retention(self) -> None:
        keep = self.config.num_to_keep
        if keep is None:
            return
        while len(self._registered) > keep:
            victim = self._registered.pop(0)
            shutil.rmtree(victim, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        return Checkpoint(self._registered[-1]) if self._registered else None
