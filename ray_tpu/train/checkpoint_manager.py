"""Checkpoint registration + retention (ref: train/v2/_internal/execution/
checkpoint/checkpoint_manager.py — register reported checkpoints under the
run's storage path, keep the top-k most recent, expose the latest for
restore)."""

from __future__ import annotations

import os
import shutil
import time

from typing import List, Optional

from ._checkpoint import Checkpoint
from .config import CheckpointConfig


def _dir_bytes(path: str) -> int:
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for fname in files:
                try:
                    total += os.path.getsize(os.path.join(root, fname))
                except OSError:
                    pass
    except OSError:
        pass
    return total


def _observe_save(job: str, seconds: float, nbytes: int) -> None:
    """train_checkpoint_save_seconds + bytes: the persistence leg of a
    reported checkpoint (staging copy / blob unpack + atomic rename) —
    the ckpt-stall badput the goodput ledger names rides the session
    timeline; these series size the stall."""
    try:
        from ..util import metrics as m

        m.Histogram(
            "train_checkpoint_save_seconds",
            "checkpoint registration (copy/unpack + atomic rename)",
            boundaries=m.TRAIN_STEP_BUCKETS, tag_keys=("job",)
        ).observe(seconds, tags={"job": job})
        if nbytes > 0:
            m.Counter(
                "train_checkpoint_save_bytes_total",
                "bytes persisted by checkpoint registration",
                tag_keys=("job",)
            ).inc(nbytes, tags={"job": job})
    except Exception:  # graftlint: ignore[swallow] — telemetry
        pass  # must never fail a checkpoint


class CheckpointManager:
    def __init__(self, storage_dir: str, config: CheckpointConfig):
        self.storage_dir = storage_dir
        self.config = config
        # metrics job label: storage lives at <run_dir>/checkpoints
        self.job = os.path.basename(
            os.path.dirname(os.path.abspath(storage_dir)))
        self._registered: List[str] = []   # oldest → newest, persisted dirs
        os.makedirs(storage_dir, exist_ok=True)
        # resume support: pre-existing checkpoint dirs from a previous run.
        # In-progress staging dirs (crash mid-copy) are cleaned, never
        # registered — only atomically-renamed final dirs count.
        for name in sorted(os.listdir(storage_dir)):
            path = os.path.join(storage_dir, name)
            if name.startswith("_staging_"):
                shutil.rmtree(path, ignore_errors=True)
            elif name.startswith("checkpoint_"):
                self._registered.append(path)

    def max_step(self) -> int:
        """Highest step already persisted (resume must continue past it)."""
        best = 0
        for path in self._registered:
            name = os.path.basename(path)
            try:
                best = max(best, int(name.split("_")[-1]))
            except ValueError:
                pass
        return best

    def register(self, source_path: str, step: int) -> Checkpoint:
        """Persist a worker-reported checkpoint directory into storage.
        Copy lands in a staging dir and is renamed into place, so a crash
        mid-copy can never leave a half checkpoint that resume would trust."""
        target = os.path.join(self.storage_dir, f"checkpoint_{step:06d}")
        if os.path.abspath(source_path) != target:
            t0 = time.time()
            staging = os.path.join(self.storage_dir, f"_staging_{step:06d}")
            shutil.rmtree(staging, ignore_errors=True)
            shutil.copytree(source_path, staging)
            if os.path.exists(target):
                shutil.rmtree(target)
            os.rename(staging, target)
            _observe_save(self.job, time.time() - t0, _dir_bytes(target))
        if target not in self._registered:
            self._registered.append(target)
        self._apply_retention()
        return Checkpoint(target)

    def register_bytes(self, blob: bytes, step: int) -> Checkpoint:
        """Persist a checkpoint shipped as a tar blob (cross-node path: the
        worker's filesystem is not ours)."""
        from ._checkpoint import unpack_blob

        t0 = time.time()
        staging = os.path.join(self.storage_dir, f"_staging_{step:06d}")
        shutil.rmtree(staging, ignore_errors=True)
        unpack_blob(blob, staging)
        target = os.path.join(self.storage_dir, f"checkpoint_{step:06d}")
        if os.path.exists(target):
            shutil.rmtree(target)
        os.rename(staging, target)
        _observe_save(self.job, time.time() - t0, len(blob))
        if target not in self._registered:
            self._registered.append(target)
        self._apply_retention()
        return Checkpoint(target)

    def _apply_retention(self) -> None:
        keep = self.config.num_to_keep
        if keep is None:
            return
        while len(self._registered) > keep:
            victim = self._registered.pop(0)
            shutil.rmtree(victim, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        return Checkpoint(self._registered[-1]) if self._registered else None
