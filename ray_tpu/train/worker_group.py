"""Gang-scheduled training worker group.

Reference analog: train/v2/_internal/execution/worker_group/worker_group.py:103
(start/poll_status:424/shutdown over one-actor-per-accelerator), rebuilt on
the TPU process model: ONE worker per HOST (jax is multi-controller — each
host process owns all its local chips), gang-reserved through a placement
group so a partial gang never runs (SPMD collectives compiled for a fixed
mesh cannot tolerate missing ranks, SURVEY §7.1 point 3).
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

from .config import ScalingConfig
from .session import TrainContext, _init_session, _shutdown_session
from ._checkpoint import Checkpoint


class TrainWorker:
    """Actor hosting one rank of the gang (module-level so any worker
    process can deserialize it by import)."""

    def __init__(self, rank: int, experiment_name: str):
        self.rank = rank
        self.experiment_name = experiment_name
        self._thread: Optional[threading.Thread] = None
        self._session = None
        self._error: Optional[str] = None
        self._finished = False

    def node_info(self) -> Dict[str, Any]:
        return {
            "rank": self.rank,
            "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
        }

    def pick_port(self) -> int:
        """A free TCP port on this host (rank 0: jax.distributed coordinator)."""
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def start(self, train_fn_blob: bytes, train_config: Optional[dict],
              world_size: int, coordinator_address: str,
              restore_path: Optional[str],
              restore_blob: Optional[bytes] = None,
              use_tpu: bool = False,
              start_step: int = 0) -> bool:
        """Install the session and launch the user function on a thread
        (ref: worker_group/thread_runner.py — the train_fn must not block
        the actor, which keeps serving poll()/shutdown()). ``restore_blob``
        carries the checkpoint as a tar when the controller's filesystem is
        not visible from this host; a local ``restore_path`` is used
        directly when it is. ``start_step`` is the controller's persisted
        high-water step: sessions number their steps past it so the GCS
        goodput ledger can classify post-restore replay as rework."""
        import time as _time

        restored = None
        restore_t0 = _time.time()
        restore_bytes = 0
        if restore_blob is not None:
            # the blob is ground truth from the controller — a same-named
            # local directory could be stale state from a previous run
            from ._checkpoint import unpack_blob

            restore_bytes = len(restore_blob)
            restored = Checkpoint(unpack_blob(restore_blob))
        elif restore_path and os.path.isdir(restore_path):
            restored = Checkpoint(restore_path)
        if restored is not None:
            self._observe_restore(_time.time() - restore_t0, restore_bytes)
        context = TrainContext(
            world_size=world_size,
            rank=self.rank,
            node_rank=self.rank,
            experiment_name=self.experiment_name,
            coordinator_address=coordinator_address,
            restored_checkpoint=restored,
            start_step=start_step,
        )
        self._session = _init_session(context)
        self._maybe_init_jax_distributed(context, use_tpu)
        self._enable_compilation_cache()
        train_fn = cloudpickle.loads(train_fn_blob)

        def _run():
            try:
                import inspect

                # train_fn may take (config) or nothing (ref: train v2
                # construct_train_func signature handling)
                if inspect.signature(train_fn).parameters:
                    train_fn(train_config if train_config is not None else {})
                else:
                    train_fn()
                # last-step metrics (train_step_seconds et al) would die
                # with this process otherwise: the controller kills the
                # gang as soon as poll() sees "finished", which races the
                # 2s flusher tick — so flush BEFORE flipping _finished
                self._flush_metrics()
                self._finished = True
            except BaseException:  # noqa: BLE001 — reported via poll
                self._error = traceback.format_exc()
                self._flush_metrics()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name=f"train_fn_rank{self.rank}")
        self._thread.start()
        return True

    @staticmethod
    def _flush_metrics() -> None:
        """Force-ship this process's metric deltas to the GCS now."""
        try:
            from ..util import metrics as m

            m._flush_once(force=True)
        except Exception:  # graftlint: ignore[swallow] — best-effort
            pass  # final flush; the run's result does not depend on it

    def _observe_restore(self, seconds: float, nbytes: int) -> None:
        """train_checkpoint_restore_seconds + bytes: the restore leg of
        gang-restart latency (the save leg rides the session)."""
        try:
            from ..util import metrics as m

            m.Histogram(
                "train_checkpoint_restore_seconds",
                "checkpoint restore/unpack on gang (re)start",
                boundaries=m.TRAIN_STEP_BUCKETS, tag_keys=("job",)
            ).observe(seconds, tags={"job": self.experiment_name})
            if nbytes > 0:
                m.Counter(
                    "train_checkpoint_restore_bytes_total",
                    "bytes unpacked by checkpoint restores",
                    tag_keys=("job",)
                ).inc(nbytes, tags={"job": self.experiment_name})
        except Exception:  # graftlint: ignore[swallow] — telemetry
            pass  # must never fail a gang start

    def _enable_compilation_cache(self) -> None:
        """Persistent XLA compilation cache (SURVEY §7.4 fast gang
        restart). Elastic SPMD restart = re-shard + RECOMPILE + restore;
        the recompile dominates restart-to-next-step latency, and a
        restarted gang's train step is byte-identical to the one the
        dead gang compiled — so the fresh worker processes must find it
        on disk instead of re-running XLA. Cache dir comes from
        config.mesh_compile_cache_dir (default: a shared /tmp dir).
        Harmless if jax was already initialized — the flags apply to
        subsequent compiles."""
        from .._private.config import global_config

        # per-uid default path: a fixed shared /tmp dir breaks when a
        # second user's workers can't write the first user's 0755 dir
        path = (global_config().mesh_compile_cache_dir
                or f"/tmp/ray_tpu_compile_cache_{os.getuid()}")
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # cache only compiles that cost real time — sub-second ones
            # would grow the dir without bounding restart latency
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.2)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            # an exotic jax build without the cache is a slow restart,
            # not a broken one
            pass

    def _maybe_init_jax_distributed(self, context: TrainContext,
                                    use_tpu: bool) -> None:
        """Multi-host SPMD bring-up (the NCCL-rendezvous analog, ref:
        train/torch/config.py:66 _setup_torch_process_group → here
        jax.distributed over the gang's rank-0 coordinator). Gated on the
        ScalingConfig's use_tpu — NOT on JAX_PLATFORMS, which the raylet
        sets to "cpu" for every pool worker it spawns; a TPU worker must
        first reclaim the device plane."""
        if not use_tpu:
            return
        # undo the pool-worker CPU pin so jax sees the host's chips — but
        # only if jax hasn't initialized yet in this process: a reused pool
        # worker whose earlier task touched jax is pinned to CPU for good,
        # and silently training a "TPU" gang on CPU must not happen
        import sys

        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            if "jax" in sys.modules:
                raise RuntimeError(
                    "TPU train worker landed in a process where jax was "
                    "already initialized under JAX_PLATFORMS=cpu; the device "
                    "plane cannot be reclaimed. Schedule TPU gangs onto "
                    "fresh workers (dedicated PG bundles).")
            os.environ.pop("JAX_PLATFORMS", None)
        if context.world_size <= 1 or not context.coordinator_address:
            return
        try:
            import jax

            jax.distributed.initialize(
                coordinator_address=context.coordinator_address,
                num_processes=context.world_size,
                process_id=context.rank,
            )
        except RuntimeError as e:
            # only "already initialized" (gang restart landed on a reused
            # process) is benign; real rendezvous failures must surface —
            # a silent process-local device view would make the SPMD
            # train_fn fail far from the root cause
            if "already" not in str(e).lower():
                raise

    def poll(self) -> Dict[str, Any]:
        """Status + reports since the last poll (ref: worker_group.py:424
        poll_status). Checkpoints are handed over as paths; the controller
        owns registration/retention (cross-filesystem transfer goes through
        pack_checkpoint)."""
        new_reports = []
        if self._session is not None:
            for rep in self._session.drain():
                new_reports.append({
                    "metrics": rep.metrics,
                    "checkpoint_path": rep.checkpoint.path if rep.checkpoint else None,
                    "step": rep.step,
                    "telemetry": rep.telemetry,
                })
        if self._error is not None:
            status = "errored"
        elif self._finished:
            status = "finished"
        elif self._thread is not None:
            status = "running"
        else:
            status = "idle"
        return {"rank": self.rank, "status": status, "error": self._error,
                "node_id": os.environ.get("RAY_TPU_NODE_ID", ""),
                "reports": new_reports}

    def pack_checkpoint(self, path: str) -> bytes:
        """Tar a reported checkpoint directory for a controller on another
        filesystem."""
        from ._checkpoint import pack_dir

        return pack_dir(path)

    def shutdown(self) -> bool:
        _shutdown_session()
        return True


class WorkerGroup:
    """Create/poll/tear down one gang of TrainWorker actors inside a
    placement group."""

    def __init__(self, scaling: ScalingConfig, experiment_name: str):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.pg = None
        self.workers: List[Any] = []
        self.coordinator_address = ""

    def start(self) -> None:
        from .. import remote
        from ..util import placement_group, PlacementGroupSchedulingStrategy

        n = self.scaling.num_workers
        bundle = self.scaling.worker_resources()
        self.pg = placement_group([dict(bundle) for _ in range(n)],
                                  strategy=self.scaling.placement_strategy)
        if not self.pg.wait(timeout_seconds=120):
            raise TimeoutError(
                f"placement group for {n} x {bundle} not schedulable")
        actor_cls = remote(TrainWorker)
        self.workers = [
            actor_cls.options(
                resources={k: v for k, v in bundle.items() if k != "CPU"},
                num_cpus=bundle.get("CPU", 1.0),
                max_restarts=0,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg,
                    placement_group_bundle_index=i),
            ).remote(i, self.experiment_name)
            for i in range(n)
        ]

    def gang_info(self) -> List[Dict[str, Any]]:
        from .. import get

        return get([w.node_info.remote() for w in self.workers], timeout=120)

    def start_training(self, train_fn, train_config: Optional[dict],
                       restore_path: Optional[str],
                       start_step: int = 0) -> None:
        from .. import get

        infos = self.gang_info()
        if self.scaling.num_workers > 1:
            port = get(self.workers[0].pick_port.remote(), timeout=60)
            self.coordinator_address = f"{infos[0]['hostname']}:{port}"
        # checkpoint for workers on OTHER nodes rides as a tar blob; workers
        # sharing this node's filesystem read the path directly (no n-fold
        # copy of a multi-GB checkpoint through the object store)
        local_node = self._local_node_id()
        restore_blob = None
        remote_ranks = {i for i, inf in enumerate(infos)
                        if inf["node_id"] != local_node}
        if restore_path and os.path.isdir(restore_path) and remote_ranks:
            from ._checkpoint import pack_dir

            restore_blob = pack_dir(restore_path)
        blob = cloudpickle.dumps(train_fn)
        get([
            w.start.remote(blob, train_config, self.scaling.num_workers,
                           self.coordinator_address, restore_path,
                           restore_blob if i in remote_ranks else None,
                           self.scaling.use_tpu, start_step)
            for i, w in enumerate(self.workers)
        ], timeout=300)

    @staticmethod
    def _local_node_id() -> str:
        from .. import _worker_api

        node = _worker_api.node()
        if node is not None:
            return node.node_id.hex()
        return os.environ.get("RAY_TPU_NODE_ID", "")

    def poll(self) -> List[Dict[str, Any]]:
        """One poll round; a dead or unresponsive worker surfaces as
        status='dead'. All ranks are polled concurrently — one hung worker
        must not stall failure detection on the others."""
        from .. import get
        from .. import exceptions as exc

        refs = [w.poll.remote() for w in self.workers]
        out = []
        for i, ref in enumerate(refs):
            try:
                out.append(get(ref, timeout=60))
            except (exc.ActorDiedError, exc.WorkerCrashedError,
                    exc.TaskError, exc.GetTimeoutError) as e:
                out.append({"rank": i, "status": "dead", "error": str(e),
                            "reports": []})
        return out

    def fetch_checkpoint_blob(self, rank: int, path: str) -> Optional[bytes]:
        from .. import get

        try:
            return get(self.workers[rank].pack_checkpoint.remote(path),
                       timeout=120)
        except Exception:
            return None  # worker died before handing the checkpoint over

    def shutdown(self) -> None:
        from .. import kill
        from ..util import remove_placement_group

        for worker in self.workers:
            try:
                kill(worker)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
