"""Train controller: the run-loop state machine driving a worker gang
(ref: train/v2/_internal/execution/controller/controller.py:91, run loop
:446 — SCHEDULING → RUNNING → [RESTARTING | ERRORED | FINISHED]).

TPU-first failure semantics: any rank dying kills the WHOLE gang and the
gang restarts from the latest registered checkpoint — an SPMD program
compiled for a fixed mesh cannot continue with a missing rank the way an
allreduce ring sometimes can (SURVEY §7.1 point 3). Elasticity is
therefore restart-shaped, not resize-shaped.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger("ray_tpu.train")

from .checkpoint_manager import CheckpointManager
from .config import RunConfig, ScalingConfig
from .worker_group import WorkerGroup
from ._checkpoint import Checkpoint


@dataclass
class Result:
    """Outcome of a training run (ref: ray.train.Result)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[str] = None


class TrainController:
    POLL_INTERVAL_S = 0.2

    def __init__(self, train_fn: Callable, train_config: Optional[dict],
                 scaling: ScalingConfig, run_config: RunConfig):
        self.train_fn = train_fn
        self.train_config = train_config
        self.scaling = scaling
        self.run_config = run_config
        name = run_config.name or f"run_{int(time.time())}"
        base = run_config.storage_path or "/tmp/ray_tpu_results"
        self.run_dir = os.path.join(base, name)
        os.makedirs(self.run_dir, exist_ok=True)
        self.checkpoints = CheckpointManager(
            os.path.join(self.run_dir, "checkpoints"),
            run_config.checkpoint_config)
        self.state = "INITIALIZING"
        self.restarts = 0
        self._latest_metrics: Dict[str, Any] = {}
        # a resumed run must number new checkpoints past what's already in
        # storage — restarting at 0 would overwrite old dirs in place while
        # retention still treats them as oldest
        self._global_step = self.checkpoints.max_step()

    def run(self) -> Result:
        error: Optional[str] = None
        group: Optional[WorkerGroup] = None
        try:
            while True:
                self.state = "SCHEDULING"
                group = WorkerGroup(self.scaling,
                                    os.path.basename(self.run_dir))
                try:
                    group.start()
                    restore = self.checkpoints.latest
                    group.start_training(
                        self.train_fn, self.train_config,
                        restore.path if restore else None,
                        start_step=self.checkpoints.max_step())
                    self.state = "RUNNING"
                    failure = self._poll_until_done(group)
                except Exception as e:  # gang bring-up died (e.g. a node
                    # was lost mid-schedule): a restartable failure, same as
                    # a rank dying mid-run (ref: controller.py worker-group
                    # startup failure handling)
                    failure = f"worker group failure: {e}"
                group.shutdown()
                group = None
                if failure is None:
                    self.state = "FINISHED"
                    return Result(
                        metrics=self._latest_metrics,
                        checkpoint=self.checkpoints.latest,
                        path=self.run_dir)
                if self.restarts >= self.run_config.failure_config.max_failures:
                    self.state = "ERRORED"
                    error = failure
                    return Result(
                        metrics=self._latest_metrics,
                        checkpoint=self.checkpoints.latest,
                        path=self.run_dir,
                        error=failure)
                # whole-gang restart from the latest checkpoint — the
                # replayed steps are rework, and the goodput ledger hears
                # it from us instead of inferring silence
                self._gcs_train_report({
                    "kind": "restart", "failure": failure,
                    "restore_step": self.checkpoints.max_step()})
                self.restarts += 1
                self.state = "RESTARTING"
        finally:
            if group is not None:
                group.shutdown()

    def _poll_until_done(self, group: WorkerGroup) -> Optional[str]:
        """Poll the gang until every rank finishes or any rank fails.
        Returns the failure description, or None on clean finish."""
        while True:
            statuses = group.poll()
            for status in statuses:
                self._ingest_reports(status, group)
            failed = [s for s in statuses if s["status"] in ("errored", "dead")]
            if failed:
                return (f"rank {failed[0]['rank']} "
                        f"{failed[0]['status']}: {failed[0]['error']}")
            if all(s["status"] == "finished" for s in statuses):
                return None
            time.sleep(self.POLL_INTERVAL_S)

    @staticmethod
    def _local_node_id() -> str:
        from .. import _worker_api

        node = _worker_api.node()
        if node is not None:
            return node.node_id.hex()
        return os.environ.get("RAY_TPU_NODE_ID", "")

    def _gcs_train_report(self, payload: Dict[str, Any]) -> None:
        """Forward goodput-plane traffic to the GCS ledger (job-stamped;
        best-effort — a head restart must not fail training)."""
        try:
            from .. import _worker_api

            core = _worker_api._core
            if core is None:
                return
            payload = {"job": os.path.basename(self.run_dir),
                       "world_size": self.scaling.num_workers, **payload}
            core.io.run(core.gcs.call("train_report", payload))
        except Exception:  # graftlint: ignore[swallow] — goodput
            pass  # accounting must never fail the training run

    def _ingest_reports(self, status: Dict[str, Any],
                        group: WorkerGroup) -> None:
        telemetry = [rep["telemetry"] for rep in status.get("reports", [])
                     if rep.get("telemetry") is not None]
        if telemetry:
            self._gcs_train_report({"records": telemetry})
        for rep in status.get("reports", []):
            if status["rank"] != 0:
                continue
            self._latest_metrics = rep["metrics"]
            self._global_step += 1
            path = rep.get("checkpoint_path")
            if not path:
                continue
            # only trust a local path when rank 0 is on OUR node — a
            # same-named directory here could be stale state from a
            # previous incarnation on a different host
            same_node = status.get("node_id", "") == self._local_node_id()
            if same_node and os.path.isdir(path):
                self.checkpoints.register(path, self._global_step)
            else:
                # rank 0 lives on another filesystem: ship the directory as
                # a tar blob through the worker (the reference's
                # storage-context upload role)
                blob = group.fetch_checkpoint_blob(0, path)
                if blob is not None:
                    self.checkpoints.register_bytes(blob, self._global_step)
                else:
                    logger.warning(
                        "dropping checkpoint %s from rank 0 (step %d): "
                        "worker could not hand it over before dying — a "
                        "future restart will restore an older checkpoint",
                        path, self._global_step)


class Trainer:
    """Public entry point (ref: train/v2/api/data_parallel_trainer.py:55
    DataParallelTrainer; fit():96). ``train_fn`` runs on every rank of the
    gang; inside it use ray_tpu.train.{get_context, report, get_checkpoint}.
    """

    def __init__(self, train_fn: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.train_fn = train_fn
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        controller = TrainController(
            self.train_fn, self.train_loop_config,
            self.scaling_config, self.run_config)
        return controller.run()
