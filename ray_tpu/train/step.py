"""pjit train-step factory: sharded, donated, compiled once.

This is the device-plane heart of training: given a loss function, a
mesh, and logical-axis rules, produce a jitted ``step(state, batch)``
whose inputs/outputs carry NamedShardings (params FSDP/TP-sharded, batch
dp-sharded) and whose buffers are donated, so XLA keeps params in HBM and
overlaps the grad all-reduce with the backward pass. The reference's
equivalent is torch DDP inside Train workers (ref:
train/torch/train_loop_utils.py prepare_model) — rebuilt here as GSPMD.
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import DEFAULT_RULES, logical_sharding, shard_pytree
from .telemetry import StepInstrumenter, estimate_flops_per_token  # noqa: F401
from . import session as _sess


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def _batch_sharding(mesh: Mesh, rules) -> NamedSharding:
    return logical_sharding(mesh, ("batch", "seq"), rules)


# ---- goodput-plane helpers (worker-side step instrumentation) ----------

def _batch_signature(batch) -> str:
    """Stable shape/dtype fingerprint of a batch pytree: the unit of
    XLA compilation the recompile detector keys on."""
    leaves = jax.tree.leaves(batch)
    return ",".join(f"{getattr(x, 'shape', ())}/{getattr(x, 'dtype', '?')}"
                    for x in leaves)


def _batch_tokens(batch) -> int:
    """Token count for throughput math: the ``tokens`` leaf when the
    batch names one (the lm convention), else the largest leaf."""
    if isinstance(batch, dict) and "tokens" in batch:
        return int(getattr(batch["tokens"], "size", 0))
    sizes = [int(getattr(x, "size", 0)) for x in jax.tree.leaves(batch)]
    return max(sizes, default=0)


def _compile_cache_entries() -> int:
    """Entry count of the persistent XLA compile cache dir (cold-compile
    ground truth for classify_compile)."""
    try:
        d = jax.config.jax_compilation_cache_dir
        if not d or not os.path.isdir(d):
            return 0
        return len(os.listdir(d))
    except Exception:  # graftlint: ignore[swallow] — cache probe is
        return 0  # advisory; classify_compile falls back to duration


def _note_recompile(old_sig: str, new_sig: str) -> None:
    """A NEW batch signature after the first compile: the silent
    step-time killer. Raise a WARNING cluster event naming the shape
    change (fire-and-forget — telemetry must not stall the step)."""
    try:
        from .. import _worker_api

        core = _worker_api._core
        if core is None:
            return
        core.io.spawn(core.gcs.call("report_event", {
            "source": "train", "severity": "WARNING",
            "message": ("train step recompiled: batch signature changed "
                        f"{old_sig or '<none>'} -> {new_sig}"),
            "fields": {"kind": "train_recompile",
                       "old_signature": old_sig,
                       "new_signature": new_sig}}))
    except Exception:  # graftlint: ignore[swallow] — fire-and-forget
        pass  # event; losing it must not stall the step


def opt_state_shardings(optimizer, params, param_shardings, mesh: Mesh):
    """Shardings for ``optimizer.init(params)`` output, explicitly.

    Optax first/second-moment states embed whole copies of the param
    pytree (mu/nu); any subtree whose structure matches ``params`` gets
    the param shardings leaf-for-leaf, everything else (step counters,
    scalars) replicates. ``jax.jit`` gives no mirroring guarantee on its
    own — at 8B scale replicated Adam moments would blow HBM.
    """
    pdef = jax.tree.structure(params)
    replicated = NamedSharding(mesh, P())

    def matches_params(sub) -> bool:
        try:
            return jax.tree.structure(sub) == pdef
        except Exception:
            return False

    abstract = jax.eval_shape(optimizer.init, params)
    return jax.tree.map(
        lambda sub: param_shardings if matches_params(sub) else replicated,
        abstract,
        is_leaf=lambda x: matches_params(x)
        or isinstance(x, jax.ShapeDtypeStruct))


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_axes,
    rules=DEFAULT_RULES,
    model_flops_per_token: Optional[float] = None,
):
    """Build (init_fn, step_fn) for ``loss_fn(params, batch) -> scalar``.

    init_fn(params) -> TrainState with sharded params/opt state placed on
    the mesh. step_fn(state, batch) -> (state, metrics); compiled with
    donated state so params update in place in HBM.

    ``model_flops_per_token`` (e.g. ``estimate_flops_per_token(
    cfg.n_params())``) lets the goodput ledger compute per-step MFU and
    tok/s/chip. Inside a Trainer session the returned step_fn and
    place_batch are instrumented — compile vs cache-hit vs compute phase
    attribution, recompile detection, token/flops accounting — at the
    cost of a device sync per call; outside a session they are the bare
    jitted functions.
    """
    param_shardings = lambda params: shard_pytree(
        params, param_axes, mesh, rules)

    def init_fn(params):
        ps = param_shardings(params)
        params = jax.device_put(params, ps)
        opt_sh = opt_state_shardings(optimizer, params, ps, mesh)
        opt_state = jax.jit(optimizer.init, out_shardings=opt_sh)(params)
        step0 = jnp.zeros((), jnp.int32)
        return TrainState(step=step0, params=params, opt_state=opt_state)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return TrainState(state.step + 1, params, opt_state), {
            "loss": loss, "grad_norm": gnorm, "step": state.step + 1,
        }

    from .._private.config import global_config

    instrumenter = StepInstrumenter(
        cache_entries=_compile_cache_entries,
        hit_threshold_s=global_config().train_compile_cache_hit_threshold_s,
        on_recompile=_note_recompile)

    def instrumented_step(state: TrainState, batch):
        session = _sess._session
        if session is None or not session.telemetry_on:
            return step_fn(state, batch)
        sig = _batch_signature(batch)
        out = instrumenter.run(lambda: step_fn(state, batch), sig,
                               block=jax.block_until_ready)
        last = instrumenter.last
        session.timeline.record_interval(last["phase"], last["t0"],
                                         last["t1"])
        tokens = _batch_tokens(batch)
        session.note_step(
            tokens=tokens,
            flops=(model_flops_per_token or 0.0) * tokens,
            chips=jax.local_device_count(),
            compile_kind=last["compile_kind"],
            recompile=last["recompile"],
            batch_shape=sig)
        return out

    def place_batch(batch):
        session = _sess._session
        if session is None or not session.telemetry_on:
            return jax.device_put(batch, _batch_sharding(mesh, rules))
        t0 = time.time()
        placed = jax.block_until_ready(
            jax.device_put(batch, _batch_sharding(mesh, rules)))
        session.timeline.record_interval("host_to_device", t0, time.time())
        return placed

    return init_fn, instrumented_step, place_batch


def make_eval_step(loss_fn: Callable[..., jax.Array]):
    @jax.jit
    def eval_fn(params, batch):
        return loss_fn(params, batch)

    return eval_fn
