"""pjit train-step factory: sharded, donated, compiled once.

This is the device-plane heart of training: given a loss function, a
mesh, and logical-axis rules, produce a jitted ``step(state, batch)``
whose inputs/outputs carry NamedShardings (params FSDP/TP-sharded, batch
dp-sharded) and whose buffers are donated, so XLA keeps params in HBM and
overlaps the grad all-reduce with the backward pass. The reference's
equivalent is torch DDP inside Train workers (ref:
train/torch/train_loop_utils.py prepare_model) — rebuilt here as GSPMD.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import DEFAULT_RULES, logical_sharding, shard_pytree


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def _batch_sharding(mesh: Mesh, rules) -> NamedSharding:
    return logical_sharding(mesh, ("batch", "seq"), rules)


def opt_state_shardings(optimizer, params, param_shardings, mesh: Mesh):
    """Shardings for ``optimizer.init(params)`` output, explicitly.

    Optax first/second-moment states embed whole copies of the param
    pytree (mu/nu); any subtree whose structure matches ``params`` gets
    the param shardings leaf-for-leaf, everything else (step counters,
    scalars) replicates. ``jax.jit`` gives no mirroring guarantee on its
    own — at 8B scale replicated Adam moments would blow HBM.
    """
    pdef = jax.tree.structure(params)
    replicated = NamedSharding(mesh, P())

    def matches_params(sub) -> bool:
        try:
            return jax.tree.structure(sub) == pdef
        except Exception:
            return False

    abstract = jax.eval_shape(optimizer.init, params)
    return jax.tree.map(
        lambda sub: param_shardings if matches_params(sub) else replicated,
        abstract,
        is_leaf=lambda x: matches_params(x)
        or isinstance(x, jax.ShapeDtypeStruct))


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_axes,
    rules=DEFAULT_RULES,
):
    """Build (init_fn, step_fn) for ``loss_fn(params, batch) -> scalar``.

    init_fn(params) -> TrainState with sharded params/opt state placed on
    the mesh. step_fn(state, batch) -> (state, metrics); compiled with
    donated state so params update in place in HBM.
    """
    param_shardings = lambda params: shard_pytree(
        params, param_axes, mesh, rules)

    def init_fn(params):
        ps = param_shardings(params)
        params = jax.device_put(params, ps)
        opt_sh = opt_state_shardings(optimizer, params, ps, mesh)
        opt_state = jax.jit(optimizer.init, out_shardings=opt_sh)(params)
        step0 = jnp.zeros((), jnp.int32)
        return TrainState(step=step0, params=params, opt_state=opt_state)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return TrainState(state.step + 1, params, opt_state), {
            "loss": loss, "grad_norm": gnorm, "step": state.step + 1,
        }

    def place_batch(batch):
        return jax.device_put(batch, _batch_sharding(mesh, rules))

    return init_fn, step_fn, place_batch


def make_eval_step(loss_fn: Callable[..., jax.Array]):
    @jax.jit
    def eval_fn(params, batch):
        return loss_fn(params, batch)

    return eval_fn
