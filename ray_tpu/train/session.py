"""Worker-side training session: the `ray_tpu.train.report` surface
(ref: python/ray/train/_internal/session.py — the _TrainSession singleton
each worker's train_fn talks to; report flow in
train/v2/_internal/execution/worker_group/thread_runner.py).

One session per worker process, installed by TrainWorker before the user
function runs. ``report()`` hands metrics (and optionally a checkpoint
directory) to the worker actor, which the controller polls.

Goodput plane: the session owns this rank's :class:`StepTimeline` — a
"step" is the interval between consecutive ``report()`` calls, so
``report()`` closes the step, attributes the unaccounted remainder
(``init`` before the first report, ``idle`` after), observes the
``train_step_seconds{phase=...}`` histograms, emits Perfetto train
lanes, and queues a :class:`TrainStepTelemetry` record for the
controller to forward to the GCS goodput ledger."""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ._checkpoint import Checkpoint
from .telemetry import StepTimeline, TrainStepTelemetry


@dataclass
class TrainContext:
    world_size: int
    rank: int
    node_rank: int
    experiment_name: str
    coordinator_address: str = ""     # rank-0 host:port for jax.distributed
    restored_checkpoint: Optional[Checkpoint] = None
    # global step base (controller's checkpoints.max_step()): a restarted
    # gang numbers its steps past what is already persisted, so the GCS
    # ledger can tell replayed work (rework) from new steps
    start_step: int = 0


@dataclass
class _Report:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    step: int = 0
    telemetry: Optional[TrainStepTelemetry] = None


_step_hist = None


def _step_histogram():
    """Lazy metric registration (session import must stay light — the
    wire registry imports train.telemetry in every process)."""
    global _step_hist
    if _step_hist is None:
        from ..util import metrics as m

        _step_hist = m.Histogram(
            "train_step_seconds",
            "per-phase training step time (phase=total is the step wall)",
            boundaries=m.TRAIN_STEP_BUCKETS,
            tag_keys=("job", "phase"))
    return _step_hist


class _Session:
    def __init__(self, context: TrainContext):
        self.context = context
        self.reports: List[_Report] = []
        self.lock = threading.Lock()
        self._step = context.start_step
        from .._private.config import global_config

        self.telemetry_on = bool(global_config().train_telemetry_enabled)
        self.timeline = StepTimeline()
        self._node_id = os.environ.get("RAY_TPU_NODE_ID", "")
        self._first_closed = False
        # per-step stats accumulated by the instrumented step factory
        # (several step_fn calls may land between two report()s)
        self._tokens = 0
        self._flops = 0.0
        self._chips = 1
        self._compile_kind = ""
        self._recompile = False
        self._batch_shape = ""

    def note_step(self, tokens: int = 0, flops: float = 0.0,
                  chips: int = 0, compile_kind: str = "",
                  recompile: bool = False, batch_shape: str = "") -> None:
        with self.lock:
            self._tokens += int(tokens)
            self._flops += float(flops)
            if chips:
                self._chips = max(self._chips, int(chips))
            # "cold" outranks "cache_hit": if any call this step did
            # real XLA work, the step counts as a cold compile
            if compile_kind == "cold" or not self._compile_kind:
                self._compile_kind = compile_kind or self._compile_kind
            self._recompile = self._recompile or recompile
            if batch_shape:
                self._batch_shape = batch_shape

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint]) -> None:
        with self.lock:
            self._step += 1
            telemetry = (self._close_step(self._step)
                         if self.telemetry_on else None)
            self.reports.append(
                _Report(dict(metrics), checkpoint, self._step, telemetry))

    def _close_step(self, step: int) -> TrainStepTelemetry:
        # first interval covers session install -> first report: model
        # init, sharding, jax.distributed — its remainder is init badput
        remainder_as = "idle" if self._first_closed else "init"
        self._first_closed = True
        start, end, phases, intervals = self.timeline.close(remainder_as)
        rec = TrainStepTelemetry(
            rank=self.context.rank, step=step, node_id=self._node_id,
            start_t=start, end_t=end, phases=phases,
            compile_kind=self._compile_kind, recompile=self._recompile,
            batch_shape=self._batch_shape, tokens=self._tokens,
            flops=self._flops, chips=self._chips)
        self._tokens, self._flops = 0, 0.0
        self._compile_kind, self._recompile = "", False
        self._batch_shape = ""
        try:
            self._observe(rec, intervals)
        except Exception:  # graftlint: ignore[swallow] — telemetry
            pass  # must never fail a training step
        return rec

    def _observe(self, rec: TrainStepTelemetry, intervals) -> None:
        step_hist = _step_histogram()
        job = self.context.experiment_name
        for name, secs in rec.phases.items():
            step_hist.observe(secs, tags={"job": job, "phase": name})
        step_hist.observe(max(0.0, rec.end_t - rec.start_t),
                          tags={"job": job, "phase": "total"})
        from ..util.tracing import record_lane_event, tracing_enabled

        if tracing_enabled():
            for name, t0, t1 in intervals:
                record_lane_event("train", f"s{rec.step}:{name}", t0, t1,
                                  step=rec.step, rank=rec.rank, phase=name)

    def drain(self) -> List[_Report]:
        """Hand pending reports to the poller and forget them — a long run
        reporting every step must not accumulate every metrics dict."""
        with self.lock:
            pending = self.reports
            self.reports = []
        return pending


_session: Optional[_Session] = None


def _init_session(context: TrainContext) -> _Session:
    global _session
    _session = _Session(context)
    return _session


def _shutdown_session() -> None:
    global _session
    _session = None


def _require_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.train.report/get_context can only be called inside a "
            "training function launched by a Trainer")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) to the controller
    (ref: ray.train.report). Only rank 0's checkpoint is registered.
    Also closes the current telemetry step: phase attribution between
    two report() calls rides out as one TrainStepTelemetry record."""
    _require_session().report(metrics, checkpoint)


@contextmanager
def phase(name: str):
    """Attribute the enclosed work to a named step phase (``data_wait``,
    ``collective_sync``, ``checkpoint_save``, ...). No-op outside a
    session or with train_telemetry_enabled=False — safe to leave in
    production train functions."""
    session = _session
    if session is None or not session.telemetry_on:
        yield
        return
    with session.timeline.phase(name):
        yield


def get_context() -> TrainContext:
    """World/rank info for this training worker (ref: ray.train.get_context)."""
    return _require_session().context


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from, if the controller restored one
    (ref: ray.train.get_checkpoint)."""
    return _require_session().context.restored_checkpoint
