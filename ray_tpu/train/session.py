"""Worker-side training session: the `ray_tpu.train.report` surface
(ref: python/ray/train/_internal/session.py — the _TrainSession singleton
each worker's train_fn talks to; report flow in
train/v2/_internal/execution/worker_group/thread_runner.py).

One session per worker process, installed by TrainWorker before the user
function runs. ``report()`` hands metrics (and optionally a checkpoint
directory) to the worker actor, which the controller polls."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ._checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_size: int
    rank: int
    node_rank: int
    experiment_name: str
    coordinator_address: str = ""     # rank-0 host:port for jax.distributed
    restored_checkpoint: Optional[Checkpoint] = None


@dataclass
class _Report:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    step: int = 0


class _Session:
    def __init__(self, context: TrainContext):
        self.context = context
        self.reports: List[_Report] = []
        self.lock = threading.Lock()
        self._step = 0

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint]):
        with self.lock:
            self._step += 1
            self.reports.append(_Report(dict(metrics), checkpoint, self._step))

    def drain(self) -> List[_Report]:
        """Hand pending reports to the poller and forget them — a long run
        reporting every step must not accumulate every metrics dict."""
        with self.lock:
            pending = self.reports
            self.reports = []
        return pending


_session: Optional[_Session] = None


def _init_session(context: TrainContext) -> _Session:
    global _session
    _session = _Session(context)
    return _session


def _shutdown_session() -> None:
    global _session
    _session = None


def _require_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.train.report/get_context can only be called inside a "
            "training function launched by a Trainer")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) to the controller
    (ref: ray.train.report). Only rank 0's checkpoint is registered."""
    _require_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    """World/rank info for this training worker (ref: ray.train.get_context)."""
    return _require_session().context


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from, if the controller restored one
    (ref: ray.train.get_checkpoint)."""
    return _require_session().context.restored_checkpoint
