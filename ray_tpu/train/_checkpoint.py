"""Checkpoint: a directory handle on storage (ref: python/ray/train/
_checkpoint.py:56 — a Checkpoint is a path plus helpers, not a format).

Framework-agnostic: training code writes whatever it wants into the
directory (orbax trees, numpy archives, pickled pytrees) and reports it;
the controller's CheckpointManager owns placement and retention under
``RunConfig.storage_path``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Iterator, Optional


def pack_dir(path: str) -> bytes:
    """Tar a checkpoint directory into a blob for cross-host transfer
    (the fsspec-upload role of the reference storage context)."""
    import io
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for name in sorted(os.listdir(path)):
            tar.add(os.path.join(path, name), arcname=name)
    return buf.getvalue()


def unpack_blob(blob: bytes, target: Optional[str] = None) -> str:
    """Extract a pack_dir() blob into ``target`` (or a fresh temp dir)."""
    import io
    import tarfile

    target = target or tempfile.mkdtemp(prefix="ckpt_")
    os.makedirs(target, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
        tar.extractall(target, filter="data")
    return target


class Checkpoint:
    """Handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents."""
        yield self.path

    def to_directory(self, target: Optional[str] = None) -> str:
        """Copy the checkpoint into ``target`` (or a temp dir)."""
        target = target or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(target) != self.path:
            shutil.copytree(self.path, target, dirs_exist_ok=True)
        return target

    def __repr__(self):
        return f"Checkpoint({self.path})"
