"""Checkpoint: a directory handle on storage (ref: python/ray/train/
_checkpoint.py:56 — a Checkpoint is a path plus helpers, not a format).

Framework-agnostic: training code writes whatever it wants into the
directory (orbax trees, numpy archives, pickled pytrees) and reports it;
the controller's CheckpointManager owns placement and retention under
``RunConfig.storage_path``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import Iterator, Optional


class Checkpoint:
    """Handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents."""
        yield self.path

    def to_directory(self, target: Optional[str] = None) -> str:
        """Copy the checkpoint into ``target`` (or a temp dir)."""
        target = target or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(target) != self.path:
            shutil.copytree(self.path, target, dirs_exist_ok=True)
        return target

    def __repr__(self):
        return f"Checkpoint({self.path})"
