"""Training goodput plane: per-step phase telemetry + badput ledger.

MegaScale's operating insight (PAPERS.md) is that at pod scale the
dominant wins come from *classifying* non-productive chip-time — compile,
data stalls, checkpoint stalls, straggler skew, restart rework — per step
and per host, not from shaving the compute kernels. This module is the
pure core of that plane:

* :class:`StepTimeline` — worker-side phase accounting for one training
  step (the interval between two ``train.report()`` calls). Phases are
  attributed explicitly (``train.phase("data_wait")``), by the
  instrumented step/place_batch wrappers (compile/compute/
  host_to_device), and the unattributed remainder closes to ``idle``
  (``init`` for the very first step) — so the partition always sums to
  the step wall.
* :class:`StepInstrumenter` — first call per batch signature is compile
  (cold vs persistent-cache hit via :func:`classify_compile`), later
  calls are compute; a NEW signature after the first is a recompile.
* :class:`TrainStepTelemetry` / :class:`TrainJobLedger` — the wire
  records (msgpack struct tags 18/19 in ``_private/wire.py``; all-default
  fields per the append-only schema-evolution rule).
* :class:`GoodputLedger` — the GCS-side per-job accounting fold:
  rank reports → productive-chip-seconds vs badput by cause, barrier
  straggler skew from clock-corrected per-rank start/finish deltas,
  high-water rework detection across gang restarts, per-step MFU and
  tok/s/chip from the step factory's model-flops estimate.

Everything here is stdlib-only and clock-injectable: the GCS imports it
without pulling jax, and tests drive it with synthetic clocks.
"""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# canonical per-step phases (the train_step_seconds{phase=...} label set;
# "total" is reserved for the whole-step wall histogram)
PHASES = ("data_wait", "host_to_device", "compile", "compute",
          "collective_sync", "checkpoint_save", "idle")

# phase -> badput bucket (MegaScale taxonomy). "compute" is the one
# productive phase; everything else is badput by cause. "init" and
# "rework"/"straggler" buckets are minted by the ledger itself.
BADPUT_OF_PHASE = {
    "data_wait": "data_stall",
    "host_to_device": "h2d",
    "compile": "compile",
    "collective_sync": "collective",
    "checkpoint_save": "ckpt_stall",
    "idle": "idle",
    "init": "init",
}


def estimate_flops_per_token(n_params: int) -> float:
    """Standard training-flops estimate: ~6 flops per parameter per
    token (fwd 2 + bwd 4; Kaplan et al. accounting). The step factory
    reports ``this * tokens`` per step so the ledger can compute MFU."""
    return 6.0 * float(n_params)


def classify_compile(duration_s: float, wrote_cache_entries: int,
                     hit_threshold_s: float = 0.5) -> str:
    """Cold compile vs persistent-cache hit for a first-call-per-shape.

    Ground truth when available: a compile that WROTE new entries into
    the persistent cache did real XLA work (cold). With no new entries
    the duration decides — a cache hit deserializes in well under the
    threshold, while a sub-``jax_persistent_cache_min_compile_time_secs``
    cold compile that wrote nothing is also fast and equally cheap, so
    misclassifying it as a hit costs nothing in the ledger."""
    if wrote_cache_entries > 0:
        return "cold"
    return "cache_hit" if duration_s < hit_threshold_s else "cold"


# ------------------------------------------------------------- wire records

@dataclass
class TrainStepTelemetry:
    """One rank's view of one training step (wire struct tag 18).

    ``start_t``/``end_t`` are the rank's LOCAL wall clock; the GCS
    applies ``NodeInfo.clock_offset`` (the collective-watchdog path)
    before folding, so cross-host skew is real skew, not NTP noise.
    All fields default (append-only wire evolution rule)."""

    rank: int = 0
    step: int = 0                  # global step number (start_step-based)
    node_id: str = ""
    start_t: float = 0.0
    end_t: float = 0.0
    phases: Dict[str, float] = field(default_factory=dict)
    compile_kind: str = ""         # "" | "cold" | "cache_hit"
    recompile: bool = False
    batch_shape: str = ""
    tokens: int = 0
    flops: float = 0.0
    chips: int = 1                 # local devices this rank drives


@dataclass
class TrainJobLedger:
    """API-shaped per-job goodput snapshot (wire struct tag 19): what
    ``state.train_status()`` / ``cli train`` / ``/api/train`` render.
    All fields default (append-only wire evolution rule)."""

    job: str = ""
    world_size: int = 0
    chips: int = 0                 # total chips across the gang
    started_at: float = 0.0
    updated_at: float = 0.0
    steps: int = 0
    productive_s: float = 0.0      # chip-seconds in compute
    badput_s: Dict[str, float] = field(default_factory=dict)
    tokens: int = 0
    flops: float = 0.0
    mfu: float = 0.0
    tok_per_s_per_chip: float = 0.0
    compile_count: int = 0
    cache_hit_count: int = 0
    recompile_count: int = 0
    rework_steps: int = 0
    restarts: int = 0
    rank_skew: Dict[str, float] = field(default_factory=dict)
    goodput_fraction: float = 0.0
    attributed_fraction: float = 0.0
    recent: List[Any] = field(default_factory=list)


# --------------------------------------------------------- worker-side timer

class StepTimeline:
    """Phase accounting for the interval between two ``report()`` calls.

    Single-threaded by design (lives on the train_fn thread). Phases may
    nest — time accrues to the innermost open phase, so the partition
    never double-counts. ``close()`` attributes the unaccounted
    remainder and resets for the next step."""

    MAX_INTERVALS = 256            # per-step Perfetto lane bound

    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self._start = clock()
        self._acc: Dict[str, float] = {}
        self._stack: List[List] = []        # [name, resume_t]
        self.intervals: List[Tuple[str, float, float]] = []

    @contextmanager
    def phase(self, name: str):
        self.enter(name)
        try:
            yield
        finally:
            self.exit()

    def enter(self, name: str) -> None:
        now = self._clock()
        if self._stack:                     # pause the outer phase
            top = self._stack[-1]
            self._accrue(top[0], top[1], now)
            top[1] = now
        self._stack.append([name, now])

    def exit(self) -> None:
        if not self._stack:
            return
        now = self._clock()
        name, resume = self._stack.pop()
        self._accrue(name, resume, now)
        if self._stack:                     # resume the outer phase
            self._stack[-1][1] = now

    def record_interval(self, name: str, t0: float, t1: float) -> None:
        """Attribute an externally-timed interval (instrumented step_fn /
        place_batch wrappers)."""
        self._accrue(name, t0, t1)

    def _accrue(self, name: str, t0: float, t1: float) -> None:
        dt = max(0.0, t1 - t0)
        if dt <= 0.0:
            return
        self._acc[name] = self._acc.get(name, 0.0) + dt
        if len(self.intervals) < self.MAX_INTERVALS:
            self.intervals.append((name, t0, t1))

    def close(self, remainder_as: str = "idle"
              ) -> Tuple[float, float, Dict[str, float],
                         List[Tuple[str, float, float]]]:
        """End the step: returns (start, end, phases, intervals) with the
        unattributed remainder folded into ``remainder_as``, then resets
        so the next step starts at this step's end."""
        now = self._clock()
        # phases still open (user holds a phase() across report) accrue
        # up to the boundary and stay open into the next step
        for frame in self._stack:
            self._accrue(frame[0], frame[1], now)
            frame[1] = now
        start, end = self._start, now
        phases = dict(self._acc)
        remainder = (end - start) - sum(phases.values())
        if remainder > 0.0:
            phases[remainder_as] = phases.get(remainder_as, 0.0) + remainder
        intervals = self.intervals
        self._start = now
        self._acc = {}
        self.intervals = []
        return start, end, phases, intervals


class StepInstrumenter:
    """Compile/compute attribution for a jitted step callable.

    First call per batch signature is a compile (cold vs cache-hit via
    the persistent-cache entry delta + duration threshold); later calls
    with a known signature are compute. A new signature AFTER the first
    is a recompile — the silent step-time killer this plane exists to
    name. Pure and injectable: tests drive it with plain functions."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 cache_entries: Callable[[], int] = lambda: 0,
                 hit_threshold_s: float = 0.5,
                 on_recompile: Optional[Callable[[str, str], None]] = None):
        self._clock = clock
        self._cache_entries = cache_entries
        self._hit_threshold_s = hit_threshold_s
        self._on_recompile = on_recompile
        self._seen: Dict[str, bool] = {}
        self._last_sig: Optional[str] = None
        self.last: Dict[str, Any] = {}

    def run(self, fn: Callable[[], Any], signature: str,
            block: Callable[[Any], Any] = lambda r: r) -> Any:
        new = signature not in self._seen
        recompile = new and bool(self._seen)
        before = self._cache_entries() if new else 0
        t0 = self._clock()
        out = block(fn())
        t1 = self._clock()
        if new:
            wrote = max(0, self._cache_entries() - before)
            kind = classify_compile(t1 - t0, wrote, self._hit_threshold_s)
            phase = "compile"
            self._seen[signature] = True
            if recompile and self._on_recompile is not None:
                self._on_recompile(self._last_sig or "", signature)
        else:
            kind, phase = "", "compute"
        self.last = {"phase": phase, "t0": t0, "t1": t1,
                     "compile_kind": kind, "recompile": recompile,
                     "signature": signature}
        self._last_sig = signature
        return out


# ----------------------------------------------------------- GCS-side ledger

class GoodputLedger:
    """Per-job fold of rank step reports into goodput accounting.

    Owned by the GCS (one per training job, keyed by experiment name);
    pure so tests drive it with synthetic records and clocks. A step
    folds when all ``world_size`` ranks have reported it: per-rank phase
    seconds × chips land in productive (compute) or a named badput
    bucket, barrier skew (each rank's gap to the slowest rank's
    start/finish envelope) lands in ``straggler``, and a step at or
    below the high-water mark — re-executed after a checkpoint restore —
    is pure ``rework``."""

    MAX_PENDING = 64               # in-flight (unfolded) steps kept
    HISTORY = 64                   # recent folded steps ring
    SKEW_EMA = 0.2                 # per-host straggler score smoothing

    def __init__(self, job: str, world_size: int = 1,
                 peak_flops_per_chip: float = 0.0,
                 clock: Callable[[], float] = time.time):
        self.job = job
        self.world_size = max(1, int(world_size))
        self.peak_flops_per_chip = float(peak_flops_per_chip)
        self._clock = clock
        self.started_at = clock()
        self.updated_at = self.started_at
        self.chips = 0
        self.steps = 0
        self.productive_s = 0.0
        self.badput_s: Dict[str, float] = {}
        self.wall_chip_s = 0.0     # denominator for attributed_fraction
        self.tokens = 0
        self.flops = 0.0
        self.mfu = 0.0
        self.tok_per_s_per_chip = 0.0
        self.compile_count = 0
        self.cache_hit_count = 0
        self.recompile_count = 0
        self.rework_steps = 0
        self.restarts = 0
        self.high_water = 0
        self.rank_skew: Dict[str, float] = {}
        self.recent: "collections.deque" = collections.deque(
            maxlen=self.HISTORY)
        self._pending: Dict[int, Dict[int, TrainStepTelemetry]] = {}

    # -- ingest ----------------------------------------------------------
    def add(self, rec: TrainStepTelemetry) -> None:
        self.updated_at = self._clock()
        if rec.compile_kind == "cold":
            self.compile_count += 1
        elif rec.compile_kind == "cache_hit":
            self.cache_hit_count += 1
        if rec.recompile:
            self.recompile_count += 1
        if rec.step <= 0:
            # init record: no barrier to wait for — account immediately
            chips = max(1, rec.chips)
            for name, secs in rec.phases.items():
                self._badput(BADPUT_OF_PHASE.get(name, name), secs * chips)
                self.wall_chip_s += secs * chips
            return
        slot = self._pending.setdefault(rec.step, {})
        slot[rec.rank] = rec
        if len(slot) >= self.world_size:
            self._fold(rec.step, self._pending.pop(rec.step))
        self._prune_pending()

    def restart(self, restore_step: int) -> int:
        """A gang restart restored from ``restore_step``: steps between
        there and the high-water mark WILL be re-executed. Returns the
        expected rework count; the actual chip-seconds are accounted as
        the replayed steps arrive (high-water detection)."""
        self.restarts += 1
        self._pending.clear()      # half-reported steps died with the gang
        return max(0, self.high_water - int(restore_step))

    # -- fold ------------------------------------------------------------
    def _badput(self, cause: str, chip_seconds: float) -> None:
        if chip_seconds > 0.0:
            self.badput_s[cause] = (self.badput_s.get(cause, 0.0)
                                    + chip_seconds)

    def _fold(self, step: int, ranks: Dict[int, TrainStepTelemetry]) -> None:
        recs = list(ranks.values())
        chips_total = sum(max(1, r.chips) for r in recs)
        self.chips = max(self.chips, chips_total)
        min_start = min(r.start_t for r in recs)
        max_end = max(r.end_t for r in recs)
        wall = max(0.0, max_end - min_start)
        if step <= self.high_water:
            # re-executed after a checkpoint restore: every chip-second
            # of the replay is rework, whatever phase it spent it in
            self.rework_steps += 1
            for r in recs:
                chip_s = max(0.0, r.end_t - r.start_t) * max(1, r.chips)
                self._badput("rework", chip_s)
                self.wall_chip_s += chip_s
            self.recent.append({"step": step, "wall_s": round(wall, 6),
                                "rework": True})
            return
        self.high_water = step
        self.steps += 1
        step_tokens = sum(r.tokens for r in recs)
        step_flops = sum(r.flops for r in recs)
        for r in recs:
            chips = max(1, r.chips)
            for name, secs in r.phases.items():
                if name == "compute":
                    self.productive_s += secs * chips
                else:
                    self._badput(BADPUT_OF_PHASE.get(name, name),
                                 secs * chips)
            # barrier skew: this rank's chips idle outside its own
            # [start, end] while the envelope is open (late start + early
            # finish, both against the gang envelope)
            skew = (max(0.0, r.start_t - min_start)
                    + max(0.0, max_end - r.end_t))
            self._badput("straggler", skew * chips)
            key = f"rank{r.rank}" + (f"@{r.node_id[:12]}"
                                     if r.node_id else "")
            prev = self.rank_skew.get(key)
            self.rank_skew[key] = (skew if prev is None else
                                   (1 - self.SKEW_EMA) * prev
                                   + self.SKEW_EMA * skew)
        self.wall_chip_s += wall * chips_total
        self.tokens += step_tokens
        self.flops += step_flops
        step_mfu = None
        if wall > 0.0 and chips_total > 0:
            if self.peak_flops_per_chip > 0.0 and step_flops > 0.0:
                step_mfu = step_flops / (wall * self.peak_flops_per_chip
                                         * chips_total)
                self.mfu = (step_mfu if self.steps == 1 else
                            0.7 * self.mfu + 0.3 * step_mfu)
            if step_tokens > 0:
                tps = step_tokens / (wall * chips_total)
                self.tok_per_s_per_chip = (
                    tps if self.steps == 1 else
                    0.7 * self.tok_per_s_per_chip + 0.3 * tps)
        self.recent.append({
            "step": step, "wall_s": round(wall, 6),
            "mfu": None if step_mfu is None else round(step_mfu, 4),
            "tokens": step_tokens,
            "phases": {k: round(v, 6) for k, v in sorted(
                self._merged_phases(recs).items())},
        })

    @staticmethod
    def _merged_phases(recs) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in recs:
            for name, secs in r.phases.items():
                out[name] = out.get(name, 0.0) + secs
        return out

    def _prune_pending(self) -> None:
        while len(self._pending) > self.MAX_PENDING:
            # oldest incomplete step is the one a dead rank will never
            # finish — fold what arrived into straggler-free accounting
            # would misattribute, so it is dropped
            self._pending.pop(min(self._pending))

    # -- derived views ---------------------------------------------------
    def total_badput_s(self) -> float:
        return sum(self.badput_s.values())

    def goodput_fraction(self) -> Optional[float]:
        denom = self.productive_s + self.total_badput_s()
        return (self.productive_s / denom) if denom > 0.0 else None

    def attributed_fraction(self) -> Optional[float]:
        """Fraction of observed wall-chip-seconds the ledger named
        (productive or a badput cause) — the >=90% acceptance bar."""
        if self.wall_chip_s <= 0.0:
            return None
        return min(1.0, (self.productive_s + self.total_badput_s())
                   / self.wall_chip_s)

    def to_record(self) -> TrainJobLedger:
        return TrainJobLedger(
            job=self.job, world_size=self.world_size, chips=self.chips,
            started_at=self.started_at, updated_at=self.updated_at,
            steps=self.steps, productive_s=self.productive_s,
            badput_s=dict(self.badput_s), tokens=self.tokens,
            flops=self.flops, mfu=self.mfu,
            tok_per_s_per_chip=self.tok_per_s_per_chip,
            compile_count=self.compile_count,
            cache_hit_count=self.cache_hit_count,
            recompile_count=self.recompile_count,
            rework_steps=self.rework_steps, restarts=self.restarts,
            rank_skew={k: round(v, 6)
                       for k, v in sorted(self.rank_skew.items())},
            goodput_fraction=self.goodput_fraction() or 0.0,
            attributed_fraction=self.attributed_fraction() or 0.0,
            recent=list(self.recent))

    # -- durable observability (obs checkpoint join) ---------------------
    def dump(self) -> Dict[str, Any]:
        return {
            "version": 1, "job": self.job, "world_size": self.world_size,
            "peak_flops_per_chip": self.peak_flops_per_chip,
            "started_at": self.started_at, "updated_at": self.updated_at,
            "chips": self.chips, "steps": self.steps,
            "productive_s": self.productive_s,
            "badput_s": dict(self.badput_s),
            "wall_chip_s": self.wall_chip_s,
            "tokens": self.tokens, "flops": self.flops,
            "mfu": self.mfu,
            "tok_per_s_per_chip": self.tok_per_s_per_chip,
            "compile_count": self.compile_count,
            "cache_hit_count": self.cache_hit_count,
            "recompile_count": self.recompile_count,
            "rework_steps": self.rework_steps, "restarts": self.restarts,
            "high_water": self.high_water,
            "rank_skew": dict(self.rank_skew),
            "recent": [dict(r) for r in self.recent],
        }

    def load(self, state: Dict[str, Any]) -> None:
        self.world_size = max(1, int(state.get("world_size", 1)))
        self.peak_flops_per_chip = float(
            state.get("peak_flops_per_chip", self.peak_flops_per_chip))
        self.started_at = float(state.get("started_at", self.started_at))
        self.updated_at = float(state.get("updated_at", self.updated_at))
        self.chips = int(state.get("chips", 0))
        self.steps = int(state.get("steps", 0))
        self.productive_s = float(state.get("productive_s", 0.0))
        self.badput_s = dict(state.get("badput_s") or {})
        self.wall_chip_s = float(state.get("wall_chip_s", 0.0))
        self.tokens = int(state.get("tokens", 0))
        self.flops = float(state.get("flops", 0.0))
        self.mfu = float(state.get("mfu", 0.0))
        self.tok_per_s_per_chip = float(
            state.get("tok_per_s_per_chip", 0.0))
        self.compile_count = int(state.get("compile_count", 0))
        self.cache_hit_count = int(state.get("cache_hit_count", 0))
        self.recompile_count = int(state.get("recompile_count", 0))
        self.rework_steps = int(state.get("rework_steps", 0))
        self.restarts = int(state.get("restarts", 0))
        self.high_water = int(state.get("high_water", 0))
        self.rank_skew = dict(state.get("rank_skew") or {})
        self.recent = collections.deque(
            (dict(r) for r in state.get("recent") or []),
            maxlen=self.HISTORY)
