"""ray_tpu.train: distributed training orchestration, TPU-first.

Reference analog: Ray Train v2 (ref: python/ray/train/v2/ — controller
at _internal/execution/controller/controller.py:91, worker group at
_internal/execution/worker_group/worker_group.py:103). The torch/NCCL
process-group plumbing (ref: train/torch/config.py:66) is replaced by
pjit/GSPMD over a named mesh: the "worker group" for a single slice is
the XLA program itself; actors orchestrate hosts, XLA owns chips.

Import discipline: the wire registry (_private/wire.py) imports
``train.telemetry`` in EVERY process to register the goodput structs, so
this package must import light — the step factory (which pulls jax +
optax) is exposed lazily via module ``__getattr__``.
"""

from ._checkpoint import Checkpoint
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .controller import Result, TrainController, Trainer
from .session import get_checkpoint, get_context, phase, report
from .telemetry import (PHASES, GoodputLedger, TrainJobLedger,
                        TrainStepTelemetry, estimate_flops_per_token)

__all__ = [
    "TrainState", "make_train_step", "make_eval_step",
    "estimate_flops_per_token",
    "Checkpoint", "CheckpointConfig", "FailureConfig", "RunConfig",
    "ScalingConfig", "Result", "TrainController", "Trainer",
    "get_checkpoint", "get_context", "phase", "report",
    "PHASES", "GoodputLedger", "TrainJobLedger", "TrainStepTelemetry",
]

# jax/optax-heavy step factory, loaded on first touch
_STEP_EXPORTS = ("TrainState", "make_train_step", "make_eval_step")


def __getattr__(name):
    if name in _STEP_EXPORTS:
        from . import step as _step

        return getattr(_step, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
