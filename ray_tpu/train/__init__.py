"""ray_tpu.train: distributed training orchestration, TPU-first.

Reference analog: Ray Train v2 (ref: python/ray/train/v2/ — controller
at _internal/execution/controller/controller.py:91, worker group at
_internal/execution/worker_group/worker_group.py:103). The torch/NCCL
process-group plumbing (ref: train/torch/config.py:66) is replaced by
pjit/GSPMD over a named mesh: the "worker group" for a single slice is
the XLA program itself; actors orchestrate hosts, XLA owns chips.
"""

from .step import TrainState, make_train_step, make_eval_step
from ._checkpoint import Checkpoint
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .controller import Result, TrainController, Trainer
from .session import get_checkpoint, get_context, report

__all__ = [
    "TrainState", "make_train_step", "make_eval_step",
    "Checkpoint", "CheckpointConfig", "FailureConfig", "RunConfig",
    "ScalingConfig", "Result", "TrainController", "Trainer",
    "get_checkpoint", "get_context", "report",
]
