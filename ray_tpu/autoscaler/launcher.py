"""Cluster launcher: the `ray up` / `ray down` role (ref:
python/ray/scripts/scripts.py:1378 `up`, autoscaler/command_runner.py
SSHCommandRunner, autoscaler/_private/commands.py get_or_create_head_node).

A cluster config (YAML or JSON) names a provider and the bootstrap
commands; `up()` provisions + bootstraps the head, starts it, then
brings up ``min_workers`` joined to it. All remote execution goes
through a CommandRunner seam — the real one shells ssh/scp, tests
inject a recorder — and provisioning goes through the same NodeProvider
seam the autoscaler uses, so the gcloud/TPU control logic stays
unit-testable in a zero-egress environment.

Config shape (TPU-first analog of the reference's cluster YAML):

    cluster_name: demo
    provider:
      type: manual | subprocess | tpu_queued_resources
      # manual:            {head_ip, worker_ips: [...]}
      # subprocess:        {}               (nodes on this host)
      # tpu_queued_resources: {head_ip, project, zone,
      #                        accelerator_type, runtime_version}
      #                        (head_ip: the head VM this launcher
      #                        bootstraps over ssh; slices join it)
    auth: {ssh_user: ubuntu, ssh_private_key: ~/.ssh/key.pem}
    head_setup_commands: [ ... shell ... ]
    worker_setup_commands: [ ... shell ... ]
    head_start_command: python -m ray_tpu.scripts.cli start --head --port 6380
    min_workers: 2
    worker_resources: {CPU: 4}
"""

from __future__ import annotations

import json
import os
import re
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ClusterConfig", "SSHCommandRunner", "up", "down",
           "load_cluster_config"]


def load_cluster_config(path: str) -> Dict[str, Any]:
    """YAML when pyyaml is available, JSON always (same ladder the
    conda runtime-env spec uses)."""
    with open(path) as f:
        text = f.read()
    try:
        import yaml

        out = yaml.safe_load(text)
    except ImportError:
        out = json.loads(text)
    if not isinstance(out, dict):
        raise ValueError(f"cluster config {path!r} must hold a mapping")
    return out


@dataclass
class ClusterConfig:
    cluster_name: str
    provider: Dict[str, Any]
    auth: Dict[str, str] = field(default_factory=dict)
    head_setup_commands: List[str] = field(default_factory=list)
    worker_setup_commands: List[str] = field(default_factory=list)
    head_start_command: str = ""
    head_port: int = 6380
    min_workers: int = 0
    worker_resources: Dict[str, float] = field(default_factory=dict)
    # interpreter used ON REMOTE HOSTS (manual/tpu providers): the local
    # sys.executable path is meaningless over ssh. The subprocess
    # provider (same host) uses sys.executable.
    remote_python: str = "python3"
    # full override of the worker join command ("{address}" substituted)
    worker_join_command: str = ""

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "ClusterConfig":
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown cluster config keys: {sorted(unknown)}")
        if "cluster_name" not in raw or "provider" not in raw:
            raise ValueError("cluster config needs cluster_name + provider")
        return cls(**raw)


class SSHCommandRunner:
    """Run shell on a remote host over ssh (ref: command_runner.py:7
    SSHCommandRunner). One instance per host; tests inject a fake with
    the same run() signature."""

    def __init__(self, host: str, auth: Dict[str, str]):
        self.host = host
        self.user = auth.get("ssh_user", "")
        self.key = auth.get("ssh_private_key", "")

    def _ssh_base(self) -> List[str]:
        target = f"{self.user}@{self.host}" if self.user else self.host
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "ConnectTimeout=10"]
        if self.key:
            cmd += ["-i", os.path.expanduser(self.key)]
        return cmd + [target]

    def run(self, command: str, timeout: float = 600.0) -> str:
        proc = subprocess.run(
            self._ssh_base() + [command], capture_output=True,
            text=True, timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"[{self.host}] {command!r} failed "
                f"({proc.returncode}): {proc.stderr[-1000:]}")
        return proc.stdout


class _LocalCommandRunner:
    """The subprocess provider's 'remote' is this host."""

    host = "localhost"

    def run(self, command: str, timeout: float = 600.0) -> str:
        proc = subprocess.run(["bash", "-c", command],
                              capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"[local] {command!r} failed "
                               f"({proc.returncode}): {proc.stderr[-1000:]}")
        return proc.stdout


def _runner_for(cfg: ClusterConfig, host: str, runner_factory):
    if runner_factory is not None:
        return runner_factory(host, cfg.auth)
    if cfg.provider.get("type") == "subprocess":
        return _LocalCommandRunner()
    return SSHCommandRunner(host, cfg.auth)


def up(config, runner_factory: Optional[Callable] = None) -> Dict[str, Any]:
    """Provision + bootstrap the cluster; returns {"address", "head",
    "workers"} (ref: commands.py create_or_update_cluster). Idempotence
    model: `up` on a live manual/subprocess cluster re-runs setup
    (setup commands must be idempotent, as in the reference)."""
    cfg = config if isinstance(config, ClusterConfig) \
        else ClusterConfig.from_dict(config)
    ptype = cfg.provider.get("type", "manual")

    if ptype == "manual":
        head_host = cfg.provider["head_ip"]
        # min_workers is the single worker-count knob across providers:
        # 0 means a head-only bring-up even when worker_ips are listed
        worker_hosts = list(cfg.provider.get("worker_ips", ()))[
            : cfg.min_workers]
    elif ptype == "subprocess":
        head_host = "127.0.0.1"
        worker_hosts = ["127.0.0.1"] * cfg.min_workers
    elif ptype == "tpu_queued_resources":
        if "head_ip" not in cfg.provider:
            raise ValueError(
                "tpu_queued_resources provider needs head_ip: the head "
                "runs on a plain VM this launcher bootstraps over ssh")
        head_host = cfg.provider["head_ip"]
        worker_hosts = []                      # slices join via provider
    else:
        raise ValueError(f"unknown provider type {ptype!r}")

    # --- head: setup commands, then start ---
    head = _runner_for(cfg, head_host, runner_factory)
    for command in cfg.head_setup_commands:
        head.run(command)
    head_python = shlex.quote(sys.executable) if ptype == "subprocess" \
        else cfg.remote_python
    start = cfg.head_start_command or (
        f"{head_python} -m ray_tpu.scripts.cli start "
        f"--head --port {cfg.head_port}")
    # the address must match where the head REALLY listens: an explicit
    # --port inside head_start_command wins over cfg.head_port
    port = cfg.head_port
    match = re.search(r"--port[= ](\d+)", start)
    if match:
        port = int(match.group(1))
    head.run(start)
    address = f"{head_host}:{port}"

    # --- workers ---
    workers: List[Any] = []
    if ptype == "tpu_queued_resources":
        from .providers import (TpuQueuedResourceProvider,
                                _default_gcloud_runner)

        provider = TpuQueuedResourceProvider(
            project=cfg.provider["project"],
            zone=cfg.provider["zone"],
            accelerator_type=cfg.provider["accelerator_type"],
            runtime_version=cfg.provider["runtime_version"],
            cluster_address=address,
            runner=cfg.provider.get("gcloud_runner")
            or _default_gcloud_runner,
            name_prefix=cfg.cluster_name,
            setup_commands=cfg.worker_setup_commands,
            remote_python=cfg.remote_python)
        for _ in range(cfg.min_workers):
            workers.append(provider.create_node(dict(cfg.worker_resources)))
    else:
        worker_python = shlex.quote(sys.executable) \
            if ptype == "subprocess" else cfg.remote_python
        join = cfg.worker_join_command.replace("{address}", address) \
            if cfg.worker_join_command else (
                f"{worker_python} -m ray_tpu.scripts.cli "
                f"start --address {shlex.quote(address)}")
        if not cfg.worker_join_command and cfg.worker_resources.get("CPU"):
            join += f" --num-cpus {cfg.worker_resources['CPU']}"
        for host in worker_hosts:
            runner = _runner_for(cfg, host, runner_factory)
            for command in cfg.worker_setup_commands:
                runner.run(command)
            runner.run(join)
            workers.append(host)
    return {"address": address, "head": head_host, "workers": workers}


def down(config, runner_factory: Optional[Callable] = None) -> None:
    """Tear the cluster down (ref: scripts.py `down` -> teardown_cluster):
    stop every node process on workers first, then the head."""
    cfg = config if isinstance(config, ClusterConfig) \
        else ClusterConfig.from_dict(config)
    ptype = cfg.provider.get("type", "manual")
    stop_python = shlex.quote(sys.executable) if ptype == "subprocess" \
        else cfg.remote_python
    stop = f"{stop_python} -m ray_tpu.scripts.cli stop"

    if ptype == "tpu_queued_resources":
        from .providers import (TpuQueuedResourceProvider,
                                _default_gcloud_runner)

        provider = TpuQueuedResourceProvider(
            project=cfg.provider["project"], zone=cfg.provider["zone"],
            accelerator_type=cfg.provider["accelerator_type"],
            runtime_version=cfg.provider["runtime_version"],
            cluster_address="", name_prefix=cfg.cluster_name,
            runner=cfg.provider.get("gcloud_runner")
            or _default_gcloud_runner)
        for name in provider.non_terminated_nodes():
            provider.terminate_node(name)
        worker_hosts: List[str] = []
        if "head_ip" not in cfg.provider:
            raise ValueError("tpu_queued_resources provider needs head_ip")
        head_host = cfg.provider["head_ip"]
    elif ptype == "subprocess":
        head_host = "127.0.0.1"
        worker_hosts = []   # `cli stop` on this host stops every node
    else:
        head_host = cfg.provider["head_ip"]
        worker_hosts = list(cfg.provider.get("worker_ips", ()))

    for host in worker_hosts:
        try:
            _runner_for(cfg, host, runner_factory).run(stop)
        except Exception:
            pass  # worker already gone
    _runner_for(cfg, head_host, runner_factory).run(stop)
