"""Autoscaler: demand-driven node scale-up / idle scale-down
(ref: python/ray/autoscaler/v2/ — autoscaler.py:42 Autoscaler,
v2/scheduler.py demand binpacking, v2/instance_manager/; SURVEY §2.2).

The demand signal is the queued-lease shapes every raylet reports with
its resource heartbeats (GcsServer NodeInfo.pending_demands) plus
explicit ``request_resources`` bundles in the GCS KV. Providers abstract
"where nodes come from": the in-process provider backs tests and
single-host elasticity; a cloud/pod provider implements the same three
methods against its control plane.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_KV_NS = "autoscaler"
_REQUESTS_KEY = "explicit_requests"


def request_resources(*, num_cpus: Optional[float] = None,
                      bundles: Optional[List[Dict[str, float]]] = None) -> None:
    """Pin a demand floor (ref: ray.autoscaler.sdk.request_resources):
    the autoscaler scales as if these bundles were always queued."""
    from .. import _worker_api

    shapes: List[Dict[str, float]] = list(bundles or [])
    if num_cpus:
        shapes.append({"CPU": float(num_cpus)})
    core = _worker_api.core()
    core.io.run(core.gcs.call("kv_put", {
        "ns": _KV_NS, "key": _REQUESTS_KEY,
        "value": json.dumps(shapes).encode()}))


class NodeProvider:
    """Minimal provider surface (ref: autoscaler/node_provider.py)."""

    def create_node(self, resources: Dict[str, float]) -> Any:
        raise NotImplementedError

    def terminate_node(self, handle: Any) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Any]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Adds/removes in-process worker nodes on the current cluster —
    the cluster_utils-backed provider used by tests and the fake
    multi-node mode (ref: autoscaler/_private/fake_multi_node)."""

    def __init__(self, cluster):
        self.cluster = cluster  # ray_tpu.cluster_utils.Cluster
        self._nodes: List[Any] = []

    def create_node(self, resources: Dict[str, float]) -> Any:
        node = self.cluster.add_node(resources=dict(resources))
        self._nodes.append(node)
        return node

    def terminate_node(self, handle: Any) -> None:
        if handle in self._nodes:
            self._nodes.remove(handle)
        self.cluster.remove_node(handle, allow_graceful=True)

    def non_terminated_nodes(self) -> List[Any]:
        return list(self._nodes)


@dataclass
class AutoscalerConfig:
    worker_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    max_workers: int = 8
    min_workers: int = 0
    idle_timeout_s: float = 30.0
    reconcile_interval_s: float = 1.0


class Autoscaler:
    """One reconcile loop: pending demands -> launch; idle -> terminate.

    Runs wherever the head runs (a thread here; the reference runs it in
    the monitor process). Call update() manually in tests, or start().
    """

    def __init__(self, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self._idle_since: Dict[str, float] = {}
        self._handle_by_node_id: Dict[str, Any] = {}
        self._launched = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- cluster view ---

    def _nodes(self) -> List[dict]:
        from .. import nodes

        return nodes()

    def _explicit_requests(self) -> List[Dict[str, float]]:
        from .. import _worker_api

        core = _worker_api.core()
        raw = core.io.run(core.gcs.call(
            "kv_get", {"ns": _KV_NS, "key": _REQUESTS_KEY}))
        return json.loads(raw) if raw else []

    @staticmethod
    def _fits(shape: Dict[str, float], avail: Dict[str, float]) -> bool:
        return all(avail.get(k, 0.0) >= v for k, v in shape.items())

    @classmethod
    def _pack(cls, shapes: List[Dict[str, float]],
              bins: List[Dict[str, float]],
              template: Optional[Dict[str, float]] = None,
              max_new_bins: Optional[int] = None):
        """First-fit packing: place each shape into an existing bin,
        else open a new ``template`` bin (when allowed). Mutates
        ``bins`` in place; returns (n_bins_opened, unplaced_shapes)."""
        opened = 0
        unplaced: List[Dict[str, float]] = []
        for shape in shapes:
            placed = False
            for av in bins:
                if cls._fits(shape, av):
                    for k, v in shape.items():
                        av[k] = av.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            can_open = (template is not None
                        and cls._fits(shape, template)
                        and (max_new_bins is None or opened < max_new_bins))
            if can_open:
                av = dict(template)
                for k, v in shape.items():
                    av[k] = av.get(k, 0.0) - v
                bins.append(av)
                opened += 1
            else:
                unplaced.append(shape)
        return opened, unplaced

    # --- one reconcile round ---

    def update(self) -> Dict[str, int]:
        """Returns {"launched": n, "terminated": m} for observability."""
        view = [n for n in self._nodes() if n["Alive"]]
        launched = terminated = 0

        # 1. collect unmet demand: queued lease shapes + explicit floor
        demands: List[Dict[str, float]] = []
        for n in view:
            demands.extend(n.get("PendingDemands", []))
        demands.extend(self._explicit_requests())
        # simulate packing demands onto current availability; whatever
        # doesn't fit drives scale-up (ref: v2/scheduler.py binpacking)
        avails = [dict(n["Available"]) for n in view]
        _, unmet = self._pack(demands, avails)

        # bin-pack the unmet shapes onto hypothetical new worker nodes
        # of the configured template; launch exactly that many
        workers = self.provider.non_terminated_nodes()
        opened, _ = self._pack(
            unmet, [], template=self.config.worker_resources,
            max_new_bins=max(0, self.config.max_workers - len(workers)))
        for _ in range(opened):
            self.provider.create_node(dict(self.config.worker_resources))
            launched += 1

        # 2. idle scale-down (never below min_workers; never the head;
        # never below the node count the explicit-request floor packs
        # onto — terminating those would flap: relaunch next round)
        floor_nodes, _ = self._pack(
            self._explicit_requests(), [],
            template=self.config.worker_resources)
        now = time.monotonic()
        provider_nodes = self.provider.non_terminated_nodes()
        by_id = {getattr(h, "node_id", None) and h.node_id.hex(): h
                 for h in provider_nodes}
        for n in view:
            handle = by_id.get(n["NodeID"])
            if handle is None:
                continue  # head or externally-managed node
            idle = (n["Available"] == n["Resources"]
                    and not n.get("PendingDemands"))
            if not idle:
                self._idle_since.pop(n["NodeID"], None)
                continue
            since = self._idle_since.setdefault(n["NodeID"], now)
            if (now - since >= self.config.idle_timeout_s
                    and len(provider_nodes) - terminated
                    > max(self.config.min_workers, floor_nodes)):
                self.provider.terminate_node(handle)
                self._idle_since.pop(n["NodeID"], None)
                terminated += 1
        return {"launched": launched, "terminated": terminated}

    # --- background loop ---

    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.config.reconcile_interval_s):
                try:
                    self.update()
                except Exception:
                    pass  # a transient RPC failure must not kill the loop

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="ray_tpu_autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


__all__ = ["Autoscaler", "AutoscalerConfig", "NodeProvider",
           "LocalNodeProvider", "request_resources"]
