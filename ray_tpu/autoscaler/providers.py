"""Node providers beyond the in-process one (ref:
python/ray/autoscaler/node_provider.py implementations —
autoscaler/{local,gcp,kuberay}/).

* SubprocessNodeProvider — real worker-NODE processes on this host,
  launched through the CLI (`python -m ray_tpu.scripts.cli start
  --address ...`). The process-level analog of LocalNodeProvider: nodes
  survive the autoscaler, die with terminate_node, and register through
  the same GCS path a remote host would.
* TpuQueuedResourceProvider — GCP TPU slices via `gcloud compute tpus
  queued-resources` (ref: the TPU pod scheduling the reference models
  with TPU-<type>-head resources + the GKE/kuberay providers). The
  command layer is injectable, so control logic is unit-testable in a
  zero-egress environment; with the real default runner it shells out
  to gcloud.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from . import NodeProvider


class SubprocessNodeProvider(NodeProvider):
    """Worker nodes as real subprocesses joined to a live cluster."""

    def __init__(self, address: str, *,
                 startup_timeout_s: float = 60.0):
        self.address = address
        self.startup_timeout_s = startup_timeout_s
        self._procs: List[subprocess.Popen] = []

    def create_node(self, resources: Dict[str, float]) -> Any:
        import tempfile

        cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
               "--address", self.address, "--block"]
        if "CPU" in resources:
            cmd += ["--num-cpus", str(resources["CPU"])]
        env = dict(os.environ)
        env["PYTHONUNBUFFERED"] = "1"
        # logs go to a FILE, never a pipe: nobody drains a pipe after
        # startup, and a full pipe buffer would wedge the node mid-run
        log = tempfile.NamedTemporaryFile(
            mode="w+b", prefix="ray_tpu_node_", suffix=".log",
            delete=False)
        proc = subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        proc._rtpu_log_path = log.name  # type: ignore[attr-defined]
        log.close()
        # poll the log for the node-up line (a blocking readline would
        # defeat the deadline when the child hangs silently)
        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline:
            with open(log.name, "rb") as f:
                content = f.read().decode(errors="replace")
            if "node up:" in content:
                break
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker node exited at startup: {content[-500:]}")
            time.sleep(0.2)
        else:
            proc.kill()
            raise TimeoutError("worker node startup timed out")
        self._procs.append(proc)
        return proc

    def terminate_node(self, handle: Any) -> None:
        if handle in self._procs:
            self._procs.remove(handle)
        handle.terminate()
        try:
            handle.wait(timeout=10)
        except subprocess.TimeoutExpired:
            handle.kill()

    def non_terminated_nodes(self) -> List[Any]:
        self._procs = [p for p in self._procs if p.poll() is None]
        return list(self._procs)


def _default_gcloud_runner(cmd: List[str]) -> str:
    return subprocess.check_output(cmd, text=True,
                                   stderr=subprocess.STDOUT)


class TpuQueuedResourceProvider(NodeProvider):
    """TPU slices through the queued-resources API.

    create_node provisions one slice (`accelerator_type` e.g.
    "v5litepod-8", `runtime_version` the TPU VM image) whose startup
    script joins this cluster; terminate_node deletes the queued
    resource; non_terminated_nodes lists live ones. ``runner`` executes
    the gcloud command line and returns stdout — inject a fake to test
    control logic without GCP access.
    """

    def __init__(self, *, project: str, zone: str, accelerator_type: str,
                 runtime_version: str, cluster_address: str,
                 runner: Callable[[List[str]], str] = _default_gcloud_runner,
                 name_prefix: str = "ray-tpu",
                 setup_commands: Optional[List[str]] = None,
                 remote_python: str = "python3"):
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.cluster_address = cluster_address
        self.runner = runner
        self.name_prefix = name_prefix
        self.setup_commands = list(setup_commands or ())
        self.remote_python = remote_python
        self._nodes: Dict[str, dict] = {}

    def _base(self, *verb: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "queued-resources", *verb,
                "--project", self.project, "--zone", self.zone,
                "--quiet"]

    def create_node(self, resources: Dict[str, float]) -> Any:
        name = f"{self.name_prefix}-{uuid.uuid4().hex[:8]}"
        join = (f"{self.remote_python} -m ray_tpu.scripts.cli start "
                f"--address {shlex.quote(self.cluster_address)} --block")
        # && : a failed setup command must NOT let a half-bootstrapped
        # slice join and crash user tasks at import time later
        startup = " && ".join(self.setup_commands + [join])
        cmd = self._base("create", name) + [
            "--node-id", name,
            "--accelerator-type", self.accelerator_type,
            "--runtime-version", self.runtime_version,
            "--metadata", f"startup-script={startup}",
        ]
        self.runner(cmd)
        self._nodes[name] = {"name": name, "resources": dict(resources)}
        return name

    def terminate_node(self, handle: Any) -> None:
        self.runner(self._base("delete", str(handle)) + ["--force"])
        self._nodes.pop(str(handle), None)

    def non_terminated_nodes(self) -> List[Any]:
        out = self.runner(self._base("list") + ["--format", "json"])
        live = []
        try:
            for entry in json.loads(out or "[]"):
                name = entry.get("name", "").rsplit("/", 1)[-1]
                state = (entry.get("state", {}) or {}).get("state", "")
                if (name.startswith(self.name_prefix + "-")
                        and state not in ("SUSPENDED", "FAILED",
                                          "DELETING")):
                    live.append(name)
        except json.JSONDecodeError:
            live = list(self._nodes)
        return live
