"""Dataset: lazy logical plan + streaming execution (ref: python/ray/data/
dataset.py — Dataset:153, map_batches:408, streaming_split:1606,
iter_batches:4216; plan machinery in _internal/logical/ + _internal/plan.py).

Blocks are numpy-dict columnar (or simple lists); batches default to the
columnar numpy format — the form `jax.device_put` consumes directly, which
is the whole Data→HBM point on TPU."""

from __future__ import annotations

import queue
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from .block import (
    Block,
    block_num_rows,
    block_schema,
    concat_blocks,
    iter_batches as _rebatch,
    rows_of,
    slice_block,
    to_columnar,
)
from .datasource import Datasource

_DEFAULT_PARALLELISM = 8


@dataclass
class _LogicalOp:
    kind: str                     # read | refs | map_block | limit
    name: str
    args: Dict[str, Any] = field(default_factory=dict)
    remote_args: Dict[str, Any] = field(default_factory=dict)
    # per-operator execution budget (ref: _internal/execution/
    # resource_manager.py operator budgets): max_inflight caps this
    # op's concurrent tasks, memory_budget_bytes caps the summed size
    # of its in-flight input blocks
    budget: Dict[str, Any] = field(default_factory=dict)


def _norm_remote_args(kwargs: dict) -> dict:
    out = {"num_cpus": kwargs.pop("num_cpus", 1)}
    for key in ("num_tpus", "resources", "max_retries"):
        if key in kwargs:
            out[key] = kwargs.pop(key)
    if kwargs:
        raise ValueError(f"unknown remote args: {sorted(kwargs)}")
    return out


def _pop_budget(kwargs: dict) -> dict:
    """Split per-operator budget options off the ray remote args
    (concurrency/memory caps govern dispatch, not the task itself)."""
    budget = {}
    if "max_inflight" in kwargs:
        budget["max_inflight"] = int(kwargs.pop("max_inflight"))
    if "memory_budget_bytes" in kwargs:
        budget["memory_budget_bytes"] = int(
            kwargs.pop("memory_budget_bytes"))
    if "autoscale_max" in kwargs:
        budget["autoscale_max"] = int(kwargs.pop("autoscale_max"))
    return budget


class Dataset:
    """A lazy, streaming-executed distributed dataset."""

    def __init__(self, plan: List[_LogicalOp],
                 parallelism: int = _DEFAULT_PARALLELISM):
        self._plan = plan
        self._parallelism = parallelism
        self._last_stats = None

    # ------------------------------------------------------------ transforms
    def _append(self, op: _LogicalOp) -> "Dataset":
        return Dataset(self._plan + [op], self._parallelism)

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", **ray_remote_args) -> "Dataset":
        """Apply fn to batches (ref: dataset.py:408). fn: dict[str, ndarray]
        -> dict[str, ndarray] under the default numpy format."""
        budget = _pop_budget(ray_remote_args)
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            outs = []
            for batch in _rebatch(iter([block]), batch_size):
                if batch_format == "numpy":
                    batch = to_columnar(batch)
                elif batch_format == "pyarrow":
                    from .block import is_arrow, numpy_to_arrow

                    if not is_arrow(batch):  # arrow in: zero-copy pass
                        batch = numpy_to_arrow(to_columnar(batch))
                out = fn(batch)
                outs.append(out)
            return concat_blocks(outs)

        return self._append(_LogicalOp(
            "map_block", f"map_batches({getattr(fn, '__name__', 'fn')})",
            {"block_fn": block_fn}, remote_args, budget))

    def map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        budget = _pop_budget(ray_remote_args)
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            return [fn(row) for row in rows_of(block)]

        return self._append(_LogicalOp(
            "map_block", f"map({getattr(fn, '__name__', 'fn')})",
            {"block_fn": block_fn}, remote_args, budget))

    def flat_map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        budget = _pop_budget(ray_remote_args)
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            out = []
            for row in rows_of(block):
                out.extend(fn(row))
            return out

        return self._append(_LogicalOp(
            "map_block", "flat_map", {"block_fn": block_fn}, remote_args,
            budget))

    def filter(self, fn: Callable, **ray_remote_args) -> "Dataset":
        budget = _pop_budget(ray_remote_args)
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            import numpy as np

            from .block import is_arrow, is_columnar

            if is_arrow(block):
                block = to_columnar(block)
            if is_columnar(block):
                # boolean-mask the columns: schema and dtypes survive even
                # when no rows do
                mask = np.fromiter((bool(fn(row)) for row in rows_of(block)),
                                   dtype=bool, count=block_num_rows(block))
                return {k: np.asarray(v)[mask] for k, v in block.items()}
            return [row for row in block if fn(row)]

        return self._append(_LogicalOp(
            "map_block", "filter", {"block_fn": block_fn}, remote_args,
            budget))

    def select_columns(self, cols) -> "Dataset":
        """Keep only the named columns (ref: dataset.py select_columns).
        Recorded as its own logical op so the planner can push the
        projection into column-aware reads (parquet never materializes
        dropped columns — see executor._pushdown_projection)."""
        cols = list(cols)

        def block_fn(block):
            from .block import is_arrow, is_columnar

            if is_arrow(block):
                return block.select(cols)  # zero-copy projection
            if not is_columnar(block):
                raise ValueError("select_columns requires columnar blocks")
            missing = [c for c in cols if c not in block]
            if missing:
                raise KeyError(f"columns not in block: {missing}")
            return {c: block[c] for c in cols}

        return self._append(_LogicalOp(
            "map_block", f"select_columns[{','.join(cols)}]",
            {"block_fn": block_fn, "columns": cols},
            {"num_cpus": 1}))

    def limit(self, n: int) -> "Dataset":
        return self._append(_LogicalOp("limit", f"limit({n})", {"n": n},
                                       {"num_cpus": 1}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """True row-level shuffle via the push-based map/merge exchange
        (shuffle.py; ref: dataset.py:1463 random_shuffle): rows scatter
        across partitions keyed on (seed, global row index) and each
        merge applies a seeded permutation — so a fixed ``seed`` yields
        the identical row sequence on every run and for ANY input block
        layout. Rows never pass through the driver."""
        from .shuffle import ShuffleSpec

        return self._append(_LogicalOp(
            "shuffle_exchange", "random_shuffle",
            {"spec": ShuffleSpec(kind="random_shuffle",
                                 name="random_shuffle", seed=seed)}))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Re-slice the stream into exactly ``num_blocks`` near-equal
        blocks, preserving row order (ref: dataset.py:1366). Runs as a
        distributed exchange — map tasks slice each block by contiguous
        global row range, per-partition merges concat the slices — so
        the dataset is never gathered in driver memory."""
        if num_blocks < 1:
            raise ValueError("repartition() needs num_blocks >= 1")
        from .shuffle import ShuffleSpec

        return self._append(_LogicalOp(
            "shuffle_exchange", f"repartition({num_blocks})",
            {"spec": ShuffleSpec(kind="repartition",
                                 name=f"repartition({num_blocks})",
                                 num_partitions=num_blocks)}))

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Global stable sort by a key column (ref: dataset.py sort →
        sort exchange): a sampling pass estimates range boundaries, map
        tasks range-partition + pre-sort fragments, and per-partition
        merge tasks k-way-merge them into globally ordered output
        blocks. Equal keys keep their original relative order in both
        directions (descending uses a reversed-stable argsort rather
        than reversing the ascending order, which would flip ties)."""
        from .shuffle import ShuffleSpec

        return self._append(_LogicalOp(
            "shuffle_exchange", f"sort({key})",
            {"spec": ShuffleSpec(kind="sort", name=f"sort({key})",
                                 key=key, descending=descending)}))

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a key column (ref: dataset.py:2188 → GroupedData
        aggregations)."""
        from .grouped import GroupedData

        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (materializes block refs of every input;
        ref: dataset.py union)."""
        refs = list(self.iter_block_refs())
        for other in others:
            refs.extend(other.iter_block_refs())
        return Dataset([_LogicalOp("refs", "union", {"refs": refs})],
                       self._parallelism)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with equal row counts
        (ref: dataset.py zip). Blocks are realigned to the left side's
        boundaries."""
        from .. import get, put
        from .block import (block_num_rows, concat_blocks, slice_block,
                            to_columnar)

        left_refs = list(self.iter_block_refs())
        right_all = concat_blocks(
            [get(r) for r in other.iter_block_refs()])
        offset = 0
        refs = []
        for ref in left_refs:
            left = to_columnar(get(ref))
            n = block_num_rows(left)
            right = to_columnar(slice_block(right_all, offset, offset + n))
            offset += n
            merged = dict(left)
            for k, v in right.items():
                merged[k if k not in merged else f"{k}_1"] = v
            refs.append(put(merged))
        if offset != block_num_rows(right_all):
            raise ValueError(
                f"zip requires equal row counts: left {offset}, right "
                f"{block_num_rows(right_all)}")
        return Dataset([_LogicalOp("refs", "zip", {"refs": refs})],
                       self._parallelism)

    # ---------------------------------------------------------- aggregates
    def _column(self, key: str):
        import numpy as np

        parts = []
        for block in self.iter_blocks():
            col = to_columnar(block).get(key)
            if col is not None and len(col):
                parts.append(np.asarray(col))
        if not parts:
            return None
        return np.concatenate(parts)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Whole-dataset aggregation with AggregateFns (ref:
        dataset.py Dataset.aggregate) — one accumulator per agg folded
        over every block, merged, finalized into {name: value}."""
        accs = [None] * len(aggs)
        for block in self.iter_blocks():
            rows = list(rows_of(block))
            for i, agg in enumerate(aggs):
                part = agg.accumulate_block(agg.init(None), rows)
                accs[i] = part if accs[i] is None else \
                    agg.merge(accs[i], part)
        return {agg.name: agg.finalize(acc if acc is not None
                                       else agg.init(None))
                for agg, acc in zip(aggs, accs)}

    def sum(self, key: str):
        col = self._column(key)
        return None if col is None else col.sum().item()

    def min(self, key: str):
        col = self._column(key)
        return None if col is None else col.min().item()

    def max(self, key: str):
        col = self._column(key)
        return None if col is None else col.max().item()

    def mean(self, key: str):
        col = self._column(key)
        return None if col is None else col.mean().item()

    def std(self, key: str):
        col = self._column(key)
        return None if col is None else col.std().item()

    def column_stats(self, columns: List[str]) -> Dict[str, Dict[str, float]]:
        """count/mean/std/min/max for many columns in ONE pass over the
        stream (preprocessor fitting; per-column aggregate calls would
        re-execute the whole plan per statistic)."""
        import numpy as np

        acc = {c: {"count": 0, "sum": 0.0, "sumsq": 0.0,
                   "min": float("inf"), "max": float("-inf")}
               for c in columns}
        for block in self.iter_blocks():
            cols = to_columnar(block)
            for c in columns:
                if c not in cols or not len(cols[c]):
                    continue
                arr = np.asarray(cols[c], np.float64)
                a = acc[c]
                a["count"] += arr.size
                a["sum"] += float(arr.sum())
                a["sumsq"] += float(np.square(arr).sum())
                a["min"] = min(a["min"], float(arr.min()))
                a["max"] = max(a["max"], float(arr.max()))
        out = {}
        for c, a in acc.items():
            n = a["count"]
            mean = a["sum"] / n if n else 0.0
            var = max(a["sumsq"] / n - mean * mean, 0.0) if n else 0.0
            out[c] = {"count": n, "mean": mean, "std": var ** 0.5,
                      "min": a["min"] if n else None,
                      "max": a["max"] if n else None}
        return out

    # ------------------------------------------------------------ execution
    def _execute(self):
        from .executor import build_executor

        executor = build_executor(self._plan, self._parallelism)
        self._last_stats = executor
        return executor

    def iter_block_refs(self) -> Iterator[Any]:
        yield from self._execute().iter_output()

    def iter_blocks(self) -> Iterator[Block]:
        from .. import get

        for ref in self.iter_block_refs():
            yield get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Block]:
        for batch in _rebatch(self.iter_blocks(), batch_size, drop_last):
            yield to_columnar(batch) if batch_format == "numpy" else batch

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from rows_of(block)

    def iter_jax_batches(self, *, batch_size: Optional[int] = None,
                         device=None, drop_last: bool = False):
        """Batches as jax arrays with one-batch device prefetch — the
        Data→HBM path (ref: iter_torch_batches:4287, rebuilt for jax:
        the NEXT batch's host→device copy overlaps the current batch's
        compute)."""
        import jax

        pending = None
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            placed = {k: jax.device_put(v, device)
                      for k, v in batch.items()}
            if pending is not None:
                yield pending
            pending = placed
        if pending is not None:
            yield pending

    def iter_torch_batches(self, *, batch_size: Optional[int] = None,
                           drop_last: bool = False):
        """Batches as torch CPU tensors (ref: iter_torch_batches)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        """Row count via tiny per-block metadata tasks — blocks stay remote
        (ref: dataset.py count() fast path)."""
        from .. import get, remote

        @remote(num_cpus=0.25)
        def _nrows(block):
            return block_num_rows(block)

        refs = [_nrows.remote(ref) for ref in self.iter_block_refs()]
        return sum(get(refs)) if refs else 0

    def schema(self) -> Optional[dict]:
        for block in self.limit(1).iter_blocks():
            return block_schema(block)
        return None

    def to_pandas(self, limit: Optional[int] = None):
        """Materialize as one pandas DataFrame (ref: dataset.py
        to_pandas — same caveat: the whole dataset lands on the
        driver)."""
        import pandas as pd

        rows = list(self.iter_rows()) if limit is None else self.take(limit)
        return pd.DataFrame(rows)

    def materialize(self) -> "Dataset":
        """Execute now; the result holds block refs and re-iterates without
        recomputation (ref: dataset.py materialize → MaterializedDataset)."""
        refs = list(self.iter_block_refs())
        ds = Dataset([_LogicalOp("refs", "materialized", {"refs": refs})],
                     self._parallelism)
        return ds

    def split(self, n: int) -> List["Dataset"]:
        refs = list(self.iter_block_refs())
        shards: List[List[Any]] = [refs[i::n] for i in range(n)]
        return [
            Dataset([_LogicalOp("refs", f"split_{i}", {"refs": shard})],
                    self._parallelism)
            for i, shard in enumerate(shards)
        ]

    # ------------------------------------------------ row-index splits

    def _split_rows(self, bounds: Optional[List[int]] = None,
                    fractions: Optional[List[float]] = None
                    ) -> List["Dataset"]:
        """Carve at absolute row indices (or fraction-derived ones) with
        ONE plan execution — the blocks fetched here are both the row
        counter and the split material."""
        from .. import put

        blocks = list(self.iter_blocks())
        total = sum(block_num_rows(b) for b in blocks)
        if fractions is not None:
            bounds, acc = [], 0
            for f in fractions:
                acc += int(total * f)
                bounds.append(acc)
        pieces: List[List[Any]] = [[] for _ in range(len(bounds) + 1)]
        pos = 0
        for block in blocks:
            n = block_num_rows(block)
            for piece_i in range(len(pieces)):
                lo = 0 if piece_i == 0 else bounds[piece_i - 1]
                hi = bounds[piece_i] if piece_i < len(bounds) else pos + n
                s = max(lo, pos) - pos
                e = min(hi, pos + n) - pos
                if e > s:
                    pieces[piece_i].append(slice_block(block, s, e))
            pos += n
        return [
            Dataset([_LogicalOp("refs", f"rowsplit_{i}",
                                {"refs": [put(b) for b in piece]})],
                    self._parallelism)
            for i, piece in enumerate(pieces)
        ]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Split at absolute row indices (ref: dataset.py
        split_at_indices): len(indices)+1 datasets."""
        if sorted(indices) != list(indices) or any(i < 0 for i in indices):
            raise ValueError("indices must be non-negative and sorted")
        return self._split_rows(bounds=list(indices))

    def split_proportionately(self, fractions: List[float]) -> List["Dataset"]:
        """Split by fractions (ref: dataset.py split_proportionately):
        len(fractions)+1 datasets, the last taking the remainder."""
        if any(not 0 < f < 1 for f in fractions) or sum(fractions) >= 1:
            raise ValueError("fractions must be in (0,1) and sum to < 1")
        return self._split_rows(fractions=fractions)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None) -> List["Dataset"]:
        """(train, test) by fraction (ref: dataset.py train_test_split)."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        return ds.split_proportionately([1.0 - test_size])

    # ------------------------------------------------ column utilities

    def add_column(self, name: str, fn) -> "Dataset":
        """Append a computed column: fn(columnar_batch) -> array (ref:
        dataset.py add_column). map_batches already hands the fn a
        columnar dict."""
        def block_fn(batch):
            cols = dict(batch)
            cols[name] = fn(cols)
            return cols

        return self.map_batches(block_fn, batch_size=None)

    def drop_columns(self, cols) -> "Dataset":
        """Remove the named columns (ref: dataset.py drop_columns)."""
        drop = set(cols)

        def block_fn(batch):
            return {k: v for k, v in batch.items() if k not in drop}

        return self.map_batches(block_fn, batch_size=None)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        """Rename columns by {old: new} (ref: dataset.py rename_columns)."""
        def block_fn(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        return self.map_batches(block_fn, batch_size=None)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (ref: dataset.py unique).
        Row-iterated so list blocks (from_items) work too."""
        seen = set()
        for row in self.iter_rows():
            v = row[column]
            seen.add(v.item() if hasattr(v, "item") else v)
        try:
            return sorted(seen)          # natural order when comparable
        except TypeError:
            return sorted(seen, key=repr)  # mixed types: stable fallback

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (ref: dataset.py random_sample)."""
        if not 0 <= fraction <= 1:
            raise ValueError("fraction must be in [0, 1]")

        def block_fn(batch, _frac=fraction, _seed=seed):
            import numpy as np

            n = block_num_rows(batch)
            if _seed is None:
                rng = np.random.default_rng()  # fresh entropy per block
            else:
                # per-block sub-seed derived from content: a bare _seed
                # would give every block the IDENTICAL keep-mask
                # (correlated sampling). Identical duplicate blocks still
                # correlate — acceptable for a deterministic sample.
                first = np.ascontiguousarray(
                    np.asarray(next(iter(batch.values()))))
                digest = int(first.view(np.uint8)[:4096].sum()) + n
                rng = np.random.default_rng([_seed, digest])
            mask = rng.random(n) < _frac
            return {k: np.asarray(v)[mask] for k, v in batch.items()}

        return self.map_batches(block_fn, batch_size=None)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n iterators fed concurrently from ONE streaming execution
        (ref: dataset.py:1606). The returned iterators are picklable and
        pullable from any node — hand them to train workers. Dispatch is
        round-robin, so shares are equal to within one block."""
        import cloudpickle

        from .. import remote
        from .executor import SplitCoordinator

        coordinator = remote(SplitCoordinator).options(
            num_cpus=0.5, max_concurrency=n + 2,
        ).remote(cloudpickle.dumps(self._plan), self._parallelism, n)
        group = _SplitGroup(coordinator)
        return [DataIterator(coordinator, i, group) for i in range(n)]

    # --------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            table = pa.table(to_columnar(block))
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import csv
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            cols = to_columnar(block)
            keys = list(cols.keys())
            with open(os.path.join(path, f"part-{i:05d}.csv"), "w",
                      newline="") as f:
                writer = csv.writer(f)
                writer.writerow(keys)
                for row in zip(*(cols[k] for k in keys)):
                    writer.writerow(row)

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                for row in rows_of(block):
                    if hasattr(row, "items"):
                        row = {k: (v.tolist() if hasattr(v, "tolist") else v)
                               for k, v in row.items()}
                    f.write(json.dumps(row) + "\n")

    def stats(self) -> str:
        if self._last_stats is None:
            return "(not executed)"
        return "\n".join(
            f"{s.name}: {s.tasks_submitted} tasks, {s.blocks_out} blocks out"
            for s in self._last_stats.stats())

    def __repr__(self):
        names = " -> ".join(op.name for op in self._plan)
        return f"Dataset({names})"


class _SplitGroup:
    """Driver-side lifetime anchor for a SplitCoordinator actor: when the
    driver's iterators are garbage-collected, the coordinator (which holds
    CPU resources for the whole execution) is killed rather than leaked.
    The coordinator also self-exits once every split drains.

    Live groups register in a WeakSet so shutdown() can reap their
    coordinators deterministically. The finalizer alone cannot be trusted
    with this: a group collected during interpreter finalization used to
    re-enter the worker API, whose auto-init then tried to START a fresh
    cluster — Thread.start() wedges forever at that point, hanging the
    interpreter on exit."""

    def __init__(self, coordinator):
        self._coordinator = coordinator
        _live_split_groups.add(self)

    def close(self) -> None:
        """Kill the coordinator (idempotent, best-effort). Only acts
        while the runtime is up — never triggers auto-init."""
        coordinator, self._coordinator = self._coordinator, None
        if coordinator is None:
            return
        try:
            from .. import _worker_api

            if _worker_api.is_initialized():
                _worker_api.kill(coordinator)
        except Exception:
            pass

    # is_finalizing bound at class-creation: an `import sys` inside the
    # finalizer itself raises once interpreter teardown begins
    def __del__(self, _is_finalizing=__import__("sys").is_finalizing):
        if _is_finalizing():
            return  # too late to RPC; the raylet reaps the actor
        self.close()


# weak registry of groups whose coordinator is still alive —
# _worker_api.shutdown() reaps these before tearing the runtime down
_live_split_groups: "weakref.WeakSet" = weakref.WeakSet()


def _reap_split_groups() -> None:
    """Kill every live split coordinator (called by shutdown, while the
    runtime can still RPC)."""
    for group in list(_live_split_groups):
        group.close()


class DataIterator:
    """One split of a streaming execution; picklable, usable inside train
    workers (ref: data/iterator.py DataIterator /
    _internal/iterator/stream_split_iterator.py)."""

    def __init__(self, coordinator, split: int, group=None):
        self._coordinator = coordinator
        self._split = split
        self._group = group  # driver-only lifetime anchor

    def __reduce__(self):
        # shipped copies (into train workers) must NOT carry the lifetime
        # anchor — only the driver's original iterators control cleanup
        return (DataIterator, (self._coordinator, self._split))

    def iter_blocks(self) -> Iterator[Block]:
        from .. import get
        from .executor import _SENTINEL

        while True:
            block = get(self._coordinator.next_block.remote(self._split))
            if isinstance(block, str) and block == _SENTINEL:
                return
            yield block

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", drop_last: bool = False,
                     prefetch_batches: int = 2,
                     to_device: Optional[Callable[[Block], Any]] = None
                     ) -> Iterator[Any]:
        """Batches with background prefetch: the next batches are fetched —
        and `to_device` (e.g. a sharded jax.device_put) applied — on a
        prefetch thread while the caller consumes the current one. This is
        the host→HBM double-buffering path (BASELINE: "Data streams to
        HBM")."""
        finished = False

        def produce() -> Iterator[Any]:
            nonlocal finished
            for batch in _rebatch(self.iter_blocks(), batch_size, drop_last):
                if batch_format == "numpy":
                    batch = to_columnar(batch)
                yield to_device(batch) if to_device is not None else batch
            finished = True

        try:
            if prefetch_batches <= 0:
                yield from produce()
                return
            q: "queue.Queue" = queue.Queue(maxsize=prefetch_batches)
            END = object()

            def pump():
                try:
                    for item in produce():
                        q.put(item)
                    q.put(END)
                except BaseException as e:  # noqa: BLE001
                    q.put(e)

            threading.Thread(target=pump, daemon=True,
                             name=f"prefetch_split_{self._split}").start()
            while True:
                item = q.get()
                if item is END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            if not finished:
                self.stop()

    def stop(self) -> None:
        """Abandon this split mid-stream: tells the coordinator to stop
        feeding it so its full queue cannot stall the other splits. Called
        automatically when a batch loop exits early."""
        try:
            self._coordinator.release_split.remote(self._split)
        except Exception:
            pass

    def __iter__(self):
        return self.iter_batches()
