"""Dataset: lazy logical plan + streaming execution (ref: python/ray/data/
dataset.py — Dataset:153, map_batches:408, streaming_split:1606,
iter_batches:4216; plan machinery in _internal/logical/ + _internal/plan.py).

Blocks are numpy-dict columnar (or simple lists); batches default to the
columnar numpy format — the form `jax.device_put` consumes directly, which
is the whole Data→HBM point on TPU."""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from .block import (
    Block,
    block_num_rows,
    block_schema,
    concat_blocks,
    iter_batches as _rebatch,
    rows_of,
    slice_block,
    to_columnar,
)
from .datasource import Datasource

_DEFAULT_PARALLELISM = 8


@dataclass
class _LogicalOp:
    kind: str                     # read | refs | map_block | limit
    name: str
    args: Dict[str, Any] = field(default_factory=dict)
    remote_args: Dict[str, Any] = field(default_factory=dict)


def _norm_remote_args(kwargs: dict) -> dict:
    out = {"num_cpus": kwargs.pop("num_cpus", 1)}
    for key in ("num_tpus", "resources", "max_retries"):
        if key in kwargs:
            out[key] = kwargs.pop(key)
    if kwargs:
        raise ValueError(f"unknown remote args: {sorted(kwargs)}")
    return out


class Dataset:
    """A lazy, streaming-executed distributed dataset."""

    def __init__(self, plan: List[_LogicalOp],
                 parallelism: int = _DEFAULT_PARALLELISM):
        self._plan = plan
        self._parallelism = parallelism
        self._last_stats = None

    # ------------------------------------------------------------ transforms
    def _append(self, op: _LogicalOp) -> "Dataset":
        return Dataset(self._plan + [op], self._parallelism)

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", **ray_remote_args) -> "Dataset":
        """Apply fn to batches (ref: dataset.py:408). fn: dict[str, ndarray]
        -> dict[str, ndarray] under the default numpy format."""
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            outs = []
            for batch in _rebatch(iter([block]), batch_size):
                if batch_format == "numpy":
                    batch = to_columnar(batch)
                out = fn(batch)
                outs.append(out)
            return concat_blocks(outs)

        return self._append(_LogicalOp(
            "map_block", f"map_batches({getattr(fn, '__name__', 'fn')})",
            {"block_fn": block_fn}, remote_args))

    def map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            return [fn(row) for row in rows_of(block)]

        return self._append(_LogicalOp(
            "map_block", f"map({getattr(fn, '__name__', 'fn')})",
            {"block_fn": block_fn}, remote_args))

    def flat_map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            out = []
            for row in rows_of(block):
                out.extend(fn(row))
            return out

        return self._append(_LogicalOp(
            "map_block", "flat_map", {"block_fn": block_fn}, remote_args))

    def filter(self, fn: Callable, **ray_remote_args) -> "Dataset":
        remote_args = _norm_remote_args(ray_remote_args)

        def block_fn(block):
            import numpy as np

            from .block import is_columnar

            if is_columnar(block):
                # boolean-mask the columns: schema and dtypes survive even
                # when no rows do
                mask = np.fromiter((bool(fn(row)) for row in rows_of(block)),
                                   dtype=bool, count=block_num_rows(block))
                return {k: np.asarray(v)[mask] for k, v in block.items()}
            return [row for row in block if fn(row)]

        return self._append(_LogicalOp(
            "map_block", "filter", {"block_fn": block_fn}, remote_args))

    def limit(self, n: int) -> "Dataset":
        return self._append(_LogicalOp("limit", f"limit({n})", {"n": n},
                                       {"num_cpus": 1}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle: global block-order permutation + per-block row
        permutation with distinct seeds (an all-to-all barrier stage, ref:
        dataset.py:1463; full cross-block row exchange is a later round)."""
        return self._append(_LogicalOp(
            "shuffle", "random_shuffle", {"seed": seed}, {"num_cpus": 1}))

    # ------------------------------------------------------------ execution
    def _execute(self):
        from .executor import build_executor

        executor = build_executor(self._plan, self._parallelism)
        self._last_stats = executor
        return executor

    def iter_block_refs(self) -> Iterator[Any]:
        yield from self._execute().iter_output()

    def iter_blocks(self) -> Iterator[Block]:
        from .. import get

        for ref in self.iter_block_refs():
            yield get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Block]:
        for batch in _rebatch(self.iter_blocks(), batch_size, drop_last):
            yield to_columnar(batch) if batch_format == "numpy" else batch

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from rows_of(block)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        """Row count via tiny per-block metadata tasks — blocks stay remote
        (ref: dataset.py count() fast path)."""
        from .. import get, remote

        @remote(num_cpus=0.25)
        def _nrows(block):
            return block_num_rows(block)

        refs = [_nrows.remote(ref) for ref in self.iter_block_refs()]
        return sum(get(refs)) if refs else 0

    def schema(self) -> Optional[dict]:
        for block in self.limit(1).iter_blocks():
            return block_schema(block)
        return None

    def materialize(self) -> "Dataset":
        """Execute now; the result holds block refs and re-iterates without
        recomputation (ref: dataset.py materialize → MaterializedDataset)."""
        refs = list(self.iter_block_refs())
        ds = Dataset([_LogicalOp("refs", "materialized", {"refs": refs})],
                     self._parallelism)
        return ds

    def split(self, n: int) -> List["Dataset"]:
        refs = list(self.iter_block_refs())
        shards: List[List[Any]] = [refs[i::n] for i in range(n)]
        return [
            Dataset([_LogicalOp("refs", f"split_{i}", {"refs": shard})],
                    self._parallelism)
            for i, shard in enumerate(shards)
        ]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n iterators fed concurrently from ONE streaming execution
        (ref: dataset.py:1606). The returned iterators are picklable and
        pullable from any node — hand them to train workers. Dispatch is
        round-robin, so shares are equal to within one block."""
        import cloudpickle

        from .. import remote
        from .executor import SplitCoordinator

        coordinator = remote(SplitCoordinator).options(
            num_cpus=0.5, max_concurrency=n + 2,
        ).remote(cloudpickle.dumps(self._plan), self._parallelism, n)
        group = _SplitGroup(coordinator)
        return [DataIterator(coordinator, i, group) for i in range(n)]

    # --------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            table = pa.table(to_columnar(block))
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                for row in rows_of(block):
                    if hasattr(row, "items"):
                        row = {k: (v.tolist() if hasattr(v, "tolist") else v)
                               for k, v in row.items()}
                    f.write(json.dumps(row) + "\n")

    def stats(self) -> str:
        if self._last_stats is None:
            return "(not executed)"
        return "\n".join(
            f"{s.name}: {s.tasks_submitted} tasks, {s.blocks_out} blocks out"
            for s in self._last_stats.stats())

    def __repr__(self):
        names = " -> ".join(op.name for op in self._plan)
        return f"Dataset({names})"


class _SplitGroup:
    """Driver-side lifetime anchor for a SplitCoordinator actor: when the
    driver's iterators are garbage-collected, the coordinator (which holds
    CPU resources for the whole execution) is killed rather than leaked.
    The coordinator also self-exits once every split drains."""

    def __init__(self, coordinator):
        self._coordinator = coordinator

    def __del__(self):
        try:
            from .. import kill

            kill(self._coordinator)
        except Exception:
            pass


class DataIterator:
    """One split of a streaming execution; picklable, usable inside train
    workers (ref: data/iterator.py DataIterator /
    _internal/iterator/stream_split_iterator.py)."""

    def __init__(self, coordinator, split: int, group=None):
        self._coordinator = coordinator
        self._split = split
        self._group = group  # driver-only lifetime anchor

    def __reduce__(self):
        # shipped copies (into train workers) must NOT carry the lifetime
        # anchor — only the driver's original iterators control cleanup
        return (DataIterator, (self._coordinator, self._split))

    def iter_blocks(self) -> Iterator[Block]:
        from .. import get
        from .executor import _SENTINEL

        while True:
            block = get(self._coordinator.next_block.remote(self._split))
            if isinstance(block, str) and block == _SENTINEL:
                return
            yield block

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", drop_last: bool = False,
                     prefetch_batches: int = 2,
                     to_device: Optional[Callable[[Block], Any]] = None
                     ) -> Iterator[Any]:
        """Batches with background prefetch: the next batches are fetched —
        and `to_device` (e.g. a sharded jax.device_put) applied — on a
        prefetch thread while the caller consumes the current one. This is
        the host→HBM double-buffering path (BASELINE: "Data streams to
        HBM")."""
        finished = False

        def produce() -> Iterator[Any]:
            nonlocal finished
            for batch in _rebatch(self.iter_blocks(), batch_size, drop_last):
                if batch_format == "numpy":
                    batch = to_columnar(batch)
                yield to_device(batch) if to_device is not None else batch
            finished = True

        try:
            if prefetch_batches <= 0:
                yield from produce()
                return
            q: "queue.Queue" = queue.Queue(maxsize=prefetch_batches)
            END = object()

            def pump():
                try:
                    for item in produce():
                        q.put(item)
                    q.put(END)
                except BaseException as e:  # noqa: BLE001
                    q.put(e)

            threading.Thread(target=pump, daemon=True,
                             name=f"prefetch_split_{self._split}").start()
            while True:
                item = q.get()
                if item is END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            if not finished:
                self.stop()

    def stop(self) -> None:
        """Abandon this split mid-stream: tells the coordinator to stop
        feeding it so its full queue cannot stall the other splits. Called
        automatically when a batch loop exits early."""
        try:
            self._coordinator.release_split.remote(self._split)
        except Exception:
            pass

    def __iter__(self):
        return self.iter_batches()
