"""Batch LLM inference over Datasets (ref: python/ray/data/llm.py +
llm/_internal/batch/processor/ — the vLLM engine stage; native here).

The processor is a plain ``map_batches`` function; each executing worker
process lazily builds ONE engine (per model/config) and reuses it across
its batches, the analog of the reference's engine-stage actor reuse.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

_ENGINE_CACHE: Dict[Tuple, Any] = {}


def _get_engine(model: str, ecfg_items: Tuple, seed: int):
    key = (model, ecfg_items, seed)
    engine = _ENGINE_CACHE.get(key)
    if engine is None:
        import jax

        from ..llm import EngineConfig, LLMEngine
        from ..models.llama import LLAMA_CONFIGS, init_params

        cfg = LLAMA_CONFIGS[model]
        params = init_params(jax.random.PRNGKey(seed), cfg)
        engine = LLMEngine(params, cfg, EngineConfig(**dict(ecfg_items)))
        _ENGINE_CACHE[key] = engine
    return engine


def build_llm_processor(model: str = "tiny", *,
                        engine_config: Optional[dict] = None,
                        sampling: Optional[dict] = None,
                        prompt_column: str = "prompt_ids",
                        output_column: str = "output_ids",
                        seed: int = 0):
    """A batch-format processor for ``Dataset.map_batches``: reads token
    id lists from ``prompt_column``, generates with continuous batching,
    writes ``output_column``."""
    ecfg_items = tuple(sorted((engine_config or {}).items()))
    sampling = dict(sampling or {})

    def process(batch: Dict[str, List[Any]]) -> Dict[str, List[Any]]:
        from ..llm import SamplingParams

        engine = _get_engine(model, ecfg_items, seed)
        prompts = [list(map(int, p)) for p in batch[prompt_column]]
        outs = engine.generate(prompts, SamplingParams(**sampling))
        out = dict(batch)
        out[output_column] = outs
        return out

    return process
