"""ray_tpu.data: streaming distributed datasets (ref: python/ray/data/).

Blocks flow between operators as shared-memory object refs; execution is
streaming with bounded queues for backpressure; `streaming_split` feeds
training gangs with per-worker iterators that prefetch to device (HBM).
"""

from __future__ import annotations

from typing import Any, List, Optional

from .block import Block
from .dataset import DataIterator, Dataset, _LogicalOp
from .datasource import (
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONLinesDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
)
from .grouped import GroupedData

_DEFAULT_PARALLELISM = 8


def read_datasource(datasource: Datasource, *,
                    parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return Dataset([_LogicalOp("read", "read",
                               {"datasource": datasource},
                               {"num_cpus": 1})], parallelism)


def range(n: int, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def from_items(items: List[Any], *,
               parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 output_format: str = "numpy",
                 parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """``output_format="arrow"`` keeps blocks as pyarrow Tables end to
    end (zero-copy slicing/batching; ref: _internal/arrow_block.py)."""
    return read_datasource(
        ParquetDatasource(paths, columns, output_format=output_format),
        parallelism=parallelism)


def read_json(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(JSONLinesDatasource(paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_csv(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_tfrecords(paths, *, raw: bool = False,
                   parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """TFRecord files; tf.train.Example records parse natively (no
    tensorflow import — see TFRecordsDatasource). ``raw=True`` yields
    undecoded record bytes."""
    from .datasource import TFRecordsDatasource

    return read_datasource(TFRecordsDatasource(paths, raw=raw),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode: str = "RGB",
                parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Image files as {"image": HWC uint8, "path"} blocks; ``size``
    resizes at read time (ref: _internal/datasource/image_datasource.py)."""
    from .datasource import ImageDatasource

    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


__all__ = [
    "Block", "Dataset", "DataIterator", "Datasource", "ReadTask",
    "GroupedData",
    "read_datasource", "range", "from_items", "read_parquet", "read_json",
    "read_numpy", "read_csv", "read_tfrecords", "read_images",
]
