"""ray_tpu.data: streaming distributed datasets (ref: python/ray/data/).

Blocks flow between operators as shared-memory object refs; execution is
streaming with bounded queues for backpressure; `streaming_split` feeds
training gangs with per-worker iterators that prefetch to device (HBM).
"""

from __future__ import annotations

from builtins import range as _builtin_range
from typing import Any, List, Optional

from .block import Block
from .dataset import DataIterator, Dataset, _LogicalOp
from .datasource import (
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONLinesDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
)
from .aggregate import (AbsMax, AggregateFn, Count, Max, Mean, Min, Std,
                        Sum)
from .grouped import GroupedData

_DEFAULT_PARALLELISM = 8


def read_datasource(datasource: Datasource, *,
                    parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return Dataset([_LogicalOp("read", "read",
                               {"datasource": datasource},
                               {"num_cpus": 1})], parallelism)


def range(n: int, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def from_items(items: List[Any], *,
               parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 output_format: str = "numpy",
                 parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """``output_format="arrow"`` keeps blocks as pyarrow Tables end to
    end (zero-copy slicing/batching; ref: _internal/arrow_block.py)."""
    return read_datasource(
        ParquetDatasource(paths, columns, output_format=output_format),
        parallelism=parallelism)


def read_json(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(JSONLinesDatasource(paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_csv(paths, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    return read_datasource(CSVDatasource(paths), parallelism=parallelism)


def read_tfrecords(paths, *, raw: bool = False,
                   parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """TFRecord files; tf.train.Example records parse natively (no
    tensorflow import — see TFRecordsDatasource). ``raw=True`` yields
    undecoded record bytes."""
    from .datasource import TFRecordsDatasource

    return read_datasource(TFRecordsDatasource(paths, raw=raw),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode: str = "RGB",
                parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Image files as {"image": HWC uint8, "path"} blocks; ``size``
    resizes at read time (ref: _internal/datasource/image_datasource.py)."""
    from .datasource import ImageDatasource

    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


def read_text(paths, *, drop_empty_lines: bool = True,
              parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Line-per-row text files as a 'text' column."""
    from .datasource import TextDatasource

    return read_datasource(
        TextDatasource(paths, drop_empty_lines=drop_empty_lines),
        parallelism=parallelism)


def read_binary_files(paths, *,
                      parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Whole files as {'bytes', 'path'} rows."""
    from .datasource import BinaryDatasource

    return read_datasource(BinaryDatasource(paths),
                           parallelism=parallelism)


def read_sql(sql: str, connection_factory, *,
             shard_key: Optional[str] = None, shards: int = 1,
             parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Query any DB-API 2.0 database (ref: _internal/datasource/
    sql_datasource.py). ``connection_factory`` is a zero-arg callable
    run inside each read task; ``shard_key``/``shards`` split the query
    by ``key % shards`` for parallel reads."""
    from .datasource import SQLDatasource

    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_key=shard_key,
                      shards=shards),
        parallelism=parallelism)


def read_webdataset(paths, *,
                    parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """Tar shards of key-grouped samples (webdataset layout)."""
    from .datasource import WebDatasetDatasource

    return read_datasource(WebDatasetDatasource(paths),
                           parallelism=parallelism)


def from_pandas(df, *, parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """One or more pandas DataFrames as columnar blocks."""
    dfs = df if isinstance(df, (list, tuple)) else [df]
    import numpy as np

    blocks = [{str(c): np.asarray(d[c]) for c in d.columns} for d in dfs]

    class _Blocks(Datasource):
        def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
            return [ReadTask(lambda b=b: iter([b])) for b in blocks]

    return read_datasource(_Blocks(), parallelism=parallelism)


def from_huggingface(dataset, *, batch_rows: int = 4096,
                     parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """A `datasets.Dataset` (huggingface) as columnar blocks (ref:
    _internal/datasource/huggingface_datasource.py). The dataset is
    sliced into row ranges; each read task materializes its own range,
    so blocks load in parallel workers."""
    n = len(dataset)
    shard = max(1, -(-n // max(1, parallelism)))

    class _HF(Datasource):
        def get_read_tasks(self, par: int) -> List[ReadTask]:
            tasks = []
            for start in _builtin_range(0, n, shard):
                def _read(start=start):
                    import numpy as np

                    end = min(start + shard, n)
                    sl = dataset[start:end]  # dict of lists
                    out = {}
                    for k, v in sl.items():
                        try:
                            out[k] = np.asarray(v)
                        except Exception:
                            out[k] = np.asarray(v, dtype=object)
                    return iter([out])
                tasks.append(ReadTask(_read, num_rows=min(
                    shard, n - start)))
            return tasks

        def estimated_rows(self):
            return n

    return read_datasource(_HF(), parallelism=parallelism)


def from_torch(torch_dataset, *,
               parallelism: int = _DEFAULT_PARALLELISM) -> Dataset:
    """A map-style torch Dataset as {'item': ...} rows (ref:
    _internal/datasource/torch_datasource.py)."""
    n = len(torch_dataset)
    shard = max(1, -(-n // max(1, parallelism)))

    class _Torch(Datasource):
        def get_read_tasks(self, par: int) -> List[ReadTask]:
            tasks = []
            for start in _builtin_range(0, n, shard):
                def _read(start=start):
                    end = min(start + shard, n)
                    rows = [{"item": torch_dataset[i]}
                            for i in _builtin_range(start, end)]
                    return iter([rows])
                tasks.append(ReadTask(_read, num_rows=min(shard, n - start)))
            return tasks

        def estimated_rows(self):
            return n

    return read_datasource(_Torch(), parallelism=parallelism)


__all__ = [
    "Block", "Dataset", "DataIterator", "Datasource", "ReadTask",
    "GroupedData",
    "AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std", "AbsMax",
    "read_datasource", "range", "from_items", "read_parquet", "read_json",
    "read_numpy", "read_csv", "read_tfrecords", "read_images",
    "read_text", "read_binary_files", "read_sql", "read_webdataset",
    "from_pandas", "from_huggingface", "from_torch",
]
