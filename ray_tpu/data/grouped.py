"""Grouped aggregations (ref: python/ray/data/grouped_data.py —
GroupedData.count/sum/mean/min/max/map_groups over a groupby key).

The exchange is the push-based map/merge shuffle (shuffle.py): map
tasks hash-partition by group key and run map-side combiners, so only
accumulator-sized partials — never rows — cross the wire; per-partition
merge tasks combine partials and finalize one columnar block each,
sorted by key within the partition. For ``map_groups`` the rows of each
group do travel, but straight between workers through the object plane;
the driver only ever holds refs.
"""

from __future__ import annotations

from typing import Callable


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def aggregate(self, *aggs):
        """Generic user aggregations (ref: grouped_data.py:49
        ``aggregate(*AggregateFn)``): one output block per shuffle
        partition, each ``{key, agg.name...}`` columnar and sorted by
        key within the partition (keys are hash-partitioned, so global
        output order is not sorted across blocks)."""
        from .dataset import _LogicalOp
        from .shuffle import ShuffleSpec

        aggs = list(aggs)
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")
        names = ",".join(agg.name for agg in aggs)
        name = f"groupby({self._key}).aggregate({names})"
        return self._ds._append(_LogicalOp(
            "shuffle_exchange", name,
            {"spec": ShuffleSpec(kind="groupby_agg", name=name,
                                 key=self._key, aggs=aggs)}))

    def count(self):
        from .aggregate import Count

        return self.aggregate(Count())

    def sum(self, value_key: str):
        from .aggregate import Sum

        return self.aggregate(Sum(value_key))

    def mean(self, value_key: str):
        from .aggregate import Mean

        return self.aggregate(Mean(value_key))

    def min(self, value_key: str):
        from .aggregate import Min

        return self.aggregate(Min(value_key))

    def max(self, value_key: str):
        from .aggregate import Max

        return self.aggregate(Max(value_key))

    def std(self, value_key: str):
        from .aggregate import Std

        return self.aggregate(Std(value_key))

    def map_groups(self, fn: Callable):
        """Apply ``fn(rows) -> rows`` per group (ref: map_groups). Rows
        hash-partition by group key across merge workers; each merge
        applies ``fn`` to its complete groups (a group never splits
        across partitions) and emits one block of the results."""
        from .dataset import _LogicalOp
        from .shuffle import ShuffleSpec

        name = f"groupby({self._key}).map_groups"
        return self._ds._append(_LogicalOp(
            "shuffle_exchange", name,
            {"spec": ShuffleSpec(kind="groupby_map", name=name,
                                 key=self._key, fn=fn)}))
