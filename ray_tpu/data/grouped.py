"""Grouped aggregations (ref: python/ray/data/grouped_data.py —
GroupedData.count/sum/mean/min/max/map_groups over a groupby key).

The exchange is a single barrier stage: rows partition by key on the
driver-side reducer task; per-group aggregates come back as one columnar
block sorted by key (matching the reference's sorted-groupby output).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def aggregate(self, *aggs):
        """Generic user aggregations (ref: grouped_data.py:49
        ``aggregate(*AggregateFn)``). Per-block accumulation runs as one
        remote task per block — only accumulator-sized partials (not
        rows) cross the exchange — then partials merge per group and
        finalize into one sorted columnar block."""
        from .dataset import _LogicalOp

        key = self._key
        aggs = list(aggs)
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")

        def exchange(refs):
            import numpy as np

            from .. import get, put, remote
            from .block import rows_of

            def block_partials(block):
                """{group: [accumulator per agg]} for one block."""
                by_key = {}
                for row in rows_of(block):
                    k = row[key]
                    k = k.item() if hasattr(k, "item") else k
                    by_key.setdefault(k, []).append(row)
                return {
                    k: [agg.accumulate_block(agg.init(k), rows)
                        for agg in aggs]
                    for k, rows in by_key.items()}

            task = remote(num_cpus=1)(block_partials)
            partials = get([task.remote(ref) for ref in refs])
            merged = {}
            for part in partials:
                for k, accs in part.items():
                    cur = merged.get(k)
                    merged[k] = accs if cur is None else [
                        agg.merge(a, b)
                        for agg, a, b in zip(aggs, cur, accs)]
            keys_sorted = sorted(merged)
            block = {key: np.asarray(keys_sorted)}
            for i, agg in enumerate(aggs):
                block[agg.name] = np.asarray(
                    [agg.finalize(merged[k][i]) for k in keys_sorted])
            return [put(block)]

        names = ",".join(agg.name for agg in aggs)
        return self._ds._append(_LogicalOp(
            "all_to_all", f"groupby({key}).aggregate({names})",
            {"fn": exchange}))

    def _aggregate(self, name: str,
                   agg_fn: Callable, value_key: Optional[str]):
        from .dataset import Dataset, _LogicalOp

        key = self._key

        def exchange(refs):
            import numpy as np

            from .. import get, put
            from .block import rows_of

            groups: Dict[Any, List[Any]] = {}
            for ref in refs:
                for row in rows_of(get(ref)):
                    k = row[key]
                    k = k.item() if hasattr(k, "item") else k
                    groups.setdefault(k, []).append(row)
            keys_sorted = sorted(groups)
            col_name = (f"{name}({value_key})" if value_key else "count()")
            values = []
            for k in keys_sorted:
                rows = groups[k]
                if value_key is None:
                    values.append(len(rows))
                else:
                    values.append(agg_fn(
                        np.asarray([row[value_key] for row in rows])))
            block = {key: np.asarray(keys_sorted),
                     col_name: np.asarray(values)}
            return [put(block)]

        return self._ds._append(_LogicalOp(
            "all_to_all", f"groupby({key}).{name}", {"fn": exchange}))

    def count(self):
        return self._aggregate("count", None, None)

    def sum(self, value_key: str):
        import numpy as np

        return self._aggregate("sum", np.sum, value_key)

    def mean(self, value_key: str):
        import numpy as np

        return self._aggregate("mean", np.mean, value_key)

    def min(self, value_key: str):
        import numpy as np

        return self._aggregate("min", np.min, value_key)

    def max(self, value_key: str):
        import numpy as np

        return self._aggregate("max", np.max, value_key)

    def std(self, value_key: str):
        import numpy as np

        return self._aggregate("std", np.std, value_key)

    def map_groups(self, fn: Callable):
        """Apply ``fn(rows) -> rows`` per group (ref: map_groups)."""
        from .dataset import Dataset, _LogicalOp

        key = self._key

        def exchange(refs):
            from .. import get, put
            from .block import rows_of

            groups: Dict[Any, List[Any]] = {}
            for ref in refs:
                for row in rows_of(get(ref)):
                    k = row[key]
                    k = k.item() if hasattr(k, "item") else k
                    groups.setdefault(k, []).append(row)
            out = []
            for k in sorted(groups):
                result = fn(groups[k])
                out.append(put(list(result)))
            return out

        return self._ds._append(_LogicalOp(
            "all_to_all", f"groupby({key}).map_groups", {"fn": exchange}))
