"""Grouped aggregations (ref: python/ray/data/grouped_data.py —
GroupedData.count/sum/mean/min/max/map_groups over a groupby key).

The exchange is a single barrier stage: rows partition by key on the
driver-side reducer task; per-group aggregates come back as one columnar
block sorted by key (matching the reference's sorted-groupby output).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _aggregate(self, name: str,
                   agg_fn: Callable, value_key: Optional[str]):
        from .dataset import Dataset, _LogicalOp

        key = self._key

        def exchange(refs):
            import numpy as np

            from .. import get, put
            from .block import rows_of

            groups: Dict[Any, List[Any]] = {}
            for ref in refs:
                for row in rows_of(get(ref)):
                    k = row[key]
                    k = k.item() if hasattr(k, "item") else k
                    groups.setdefault(k, []).append(row)
            keys_sorted = sorted(groups)
            col_name = (f"{name}({value_key})" if value_key else "count()")
            values = []
            for k in keys_sorted:
                rows = groups[k]
                if value_key is None:
                    values.append(len(rows))
                else:
                    values.append(agg_fn(
                        np.asarray([row[value_key] for row in rows])))
            block = {key: np.asarray(keys_sorted),
                     col_name: np.asarray(values)}
            return [put(block)]

        return self._ds._append(_LogicalOp(
            "all_to_all", f"groupby({key}).{name}", {"fn": exchange}))

    def count(self):
        return self._aggregate("count", None, None)

    def sum(self, value_key: str):
        import numpy as np

        return self._aggregate("sum", np.sum, value_key)

    def mean(self, value_key: str):
        import numpy as np

        return self._aggregate("mean", np.mean, value_key)

    def min(self, value_key: str):
        import numpy as np

        return self._aggregate("min", np.min, value_key)

    def max(self, value_key: str):
        import numpy as np

        return self._aggregate("max", np.max, value_key)

    def std(self, value_key: str):
        import numpy as np

        return self._aggregate("std", np.std, value_key)

    def map_groups(self, fn: Callable):
        """Apply ``fn(rows) -> rows`` per group (ref: map_groups)."""
        from .dataset import Dataset, _LogicalOp

        key = self._key

        def exchange(refs):
            from .. import get, put
            from .block import rows_of

            groups: Dict[Any, List[Any]] = {}
            for ref in refs:
                for row in rows_of(get(ref)):
                    k = row[key]
                    k = k.item() if hasattr(k, "item") else k
                    groups.setdefault(k, []).append(row)
            out = []
            for k in sorted(groups):
                result = fn(groups[k])
                out.append(put(list(result)))
            return out

        return self._ds._append(_LogicalOp(
            "all_to_all", f"groupby({key}).map_groups", {"fn": exchange}))
