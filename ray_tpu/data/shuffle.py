"""Push-based distributed shuffle: the Data plane's all-to-all exchange.

Two-stage map/merge exchange run entirely inside the distributed object
store (ref: Exoshuffle — Luan et al. 2023, shuffle built on the task +
object-store substrate; Magnet — Shen et al., VLDB 2020, push-based
partition merging; code analog: ray/data/_internal/planner/exchange/):

  * **map** tasks partition one input block into P partition fragments
    and return them as separate task returns (``num_returns = P + 1``,
    the +1 a small metadata dict), so every fragment seals on the map
    worker's *local* store — that is the push;
  * per-partition **merge** tasks (spread-scheduled across nodes) take
    their P_i fragment refs as task dependencies and pull them through
    the bulk transfer plane — the cut-through relay + parallel spill
    restore path — emitting one merged output block per partition:
    concat for ``repartition`` (contiguous global row ranges, order
    preserving), k-way sorted merge for ``sort``, hash-merge + aggregate
    combiners for ``groupby`` (only accumulator-sized partials cross the
    wire), and a seeded row-level scatter for ``random_shuffle``.

The driver only ever holds ObjectRefs and O(P) metadata — row counts,
sampled range boundaries, fragment byte sizes. Rows never materialize in
driver memory; when the working set outgrows the store, fragments spill
and restore through the N11 parallel spill I/O plane and the exchange
records a WARNING cluster event marking the out-of-core transition.

Pipelining: hash-partitioned exchanges (groupby) know P up front, so map
fragments start pushing while upstream read/map tasks are still
producing; range/scatter exchanges overlap their sampling / row-count
probe tasks with upstream production the same way. Merge tasks are
submitted in a ``shuffle_merge_parallelism`` window *before* earlier
merges finish, so fragment pulls overlap map execution.
"""

from __future__ import annotations

import collections
import os
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .block import (block_num_rows, block_size_bytes, concat_blocks,
                    is_arrow, is_columnar, rows_of, slice_block,
                    to_columnar)

# reserved column carrying the global row index through a random_shuffle
# exchange (stripped from merge output)
_GIDX = "__shuffle_gidx__"
# evenly-spaced key samples per input block for range partitioning
_SAMPLES_PER_BLOCK = 64
# hash exchanges (groupby) use a fixed small default partition count so
# map tasks can dispatch before the input cardinality is known — the
# property that lets fragment pushes pipeline with upstream production
_GROUPBY_DEFAULT_PARTITIONS = 8
# ceiling for auto-derived partition counts (bounds num_returns fan-out)
_MAX_AUTO_PARTITIONS = 512


# ---------------------------------------------------------------------------
# spec


@dataclass
class ShuffleSpec:
    """Driver-side description of one exchange, shipped to map/merge
    tasks inside their (cloudpickled) payload arg."""

    kind: str                    # sort | repartition | random_shuffle |
    #                              groupby_agg | groupby_map
    name: str = ""
    key: Optional[str] = None    # sort / groupby key column
    descending: bool = False
    seed: Optional[int] = None   # random_shuffle
    num_partitions: int = 0      # 0 = auto; repartition pins it
    aggs: Optional[List[Any]] = None       # groupby_agg AggregateFns
    fn: Optional[Callable] = None          # groupby_map group function


# ---------------------------------------------------------------------------
# metrics (created lazily so importing this module never starts the
# metrics flusher thread in processes that never shuffle)

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _shuffle_metrics() -> Dict[str, Any]:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ..util.metrics import Counter

            _metrics = {
                "exchanges": Counter(
                    "data_shuffle_exchanges_total",
                    "shuffle exchanges run", ("op",)),
                "bytes_pushed": Counter(
                    "data_shuffle_bytes_pushed_total",
                    "fragment bytes pushed map->merge", ("op",)),
                "fragments": Counter(
                    "data_shuffle_fragments_total",
                    "non-empty partition fragments produced", ("op",)),
                "merge_tasks": Counter(
                    "data_shuffle_merge_tasks_total",
                    "per-partition merge tasks run", ("op",)),
                "spill_bytes": Counter(
                    "data_shuffle_spill_bytes_total",
                    "store spill observed during exchanges", ("op",)),
            }
        return _metrics


# ---------------------------------------------------------------------------
# deterministic hashing / stable ordering primitives


def _mix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 — a deterministic,
    well-mixed hash (Python's ``hash()`` is salted per process, useless
    for cross-worker partitioning)."""
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def _hash_scalar(value: Any) -> int:
    """Deterministic 64-bit hash of one group key (must agree with the
    vectorized column path for the same logical value)."""
    if hasattr(value, "item"):
        value = value.item()
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, int):
        raw = np.uint64(np.int64(value).view(np.uint64))
    elif isinstance(value, float):
        raw = np.uint64(np.float64(value + 0.0).view(np.uint64))
    else:
        data = value if isinstance(value, bytes) else str(value).encode()
        raw = np.uint64(zlib.crc32(data))
    return int(_mix64(np.asarray([raw]))[0])


def _hash_column(col: np.ndarray) -> np.ndarray:
    col = np.asarray(col)
    if col.dtype.kind in "iub":
        raw = col.astype(np.int64).view(np.uint64)
    elif col.dtype.kind == "f":
        # + 0.0 folds -0.0 into +0.0 so equal floats hash equal
        raw = (col.astype(np.float64) + 0.0).view(np.uint64)
    else:
        return np.asarray([_hash_scalar(v) for v in col], np.uint64)
    return _mix64(raw)


def stable_argsort(keys: np.ndarray, descending: bool = False) -> np.ndarray:
    """Stable argsort in either direction. The naive descending form —
    ``np.argsort(keys, kind="stable")[::-1]`` — reverses tie order too;
    stably sorting the *reversed* array and mapping indices back keeps
    equal keys in original order for every dtype (negation would break
    unsigned ints and strings)."""
    keys = np.asarray(keys)
    if not descending:
        return np.argsort(keys, kind="stable")
    n = len(keys)
    rev = np.argsort(keys[::-1], kind="stable")
    return (n - 1 - rev)[::-1]


def _take(block_cols: Dict[str, np.ndarray], idx: np.ndarray) -> Dict:
    return {k: np.asarray(v)[idx] for k, v in block_cols.items()}


# ---------------------------------------------------------------------------
# per-task environment metadata (spill / store-pressure observation)


def _task_env() -> Dict[str, Any]:
    """Cumulative spill counter + store-pressure flag for THIS worker
    process; the driver diffs per-pid snapshots across all exchange
    tasks to estimate how much spill the exchange itself drove."""
    out: Dict[str, Any] = {"pid": os.getpid(), "spill": 0, "hot": False}
    try:
        from .._private.object_store import IO_STATS

        out["spill"] = int(IO_STATS.get("spill_bytes", 0))
    except Exception:
        pass
    try:
        from .._private.config import global_config
        from .._worker_api import _core

        if _core is not None and getattr(_core, "store", None) is not None:
            capacity = _core.store.capacity or 1
            frac = _core.store.used_bytes() / capacity
            out["hot"] = frac >= global_config().object_spilling_threshold
    except Exception:
        pass
    return out


def _payload_bytes(obj: Any) -> int:
    try:
        return int(block_size_bytes(obj))
    except Exception:
        try:
            import cloudpickle

            return len(cloudpickle.dumps(obj))
        except Exception:
            return 0


# ---------------------------------------------------------------------------
# probe tasks (pipelined with upstream production)


def _exchange_meta_task(block):
    """Tiny (rows, bytes) probe — the only thing the driver get()s per
    input block besides merge metadata."""
    return block_num_rows(block), _payload_bytes(block)


def _exchange_sample_task(block, key, k):
    """(rows, bytes, sampled keys): up to k evenly-spaced key values for
    range-boundary estimation (ref: exchange/sort sample stage)."""
    n = block_num_rows(block)
    nb = _payload_bytes(block)
    if n == 0:
        return n, nb, np.asarray([])
    keys = np.asarray(to_columnar(block)[key])
    idx = np.linspace(0, n - 1, num=min(int(k), n)).astype(np.int64)
    return n, nb, keys[idx]


# ---------------------------------------------------------------------------
# map side: block -> P fragments


def _empty_like(block) -> Any:
    if is_columnar(block) or is_arrow(block):
        return slice_block(block, 0, 0)
    return []


def _partition_sort(block, spec: ShuffleSpec, ctx: Dict) -> List[Any]:
    P = ctx["P"]
    boundaries = np.asarray(ctx["boundaries"])
    if is_columnar(block):
        cols = to_columnar(block)
        keys = np.asarray(cols[spec.key])
        if len(boundaries):
            part = np.searchsorted(boundaries, keys, side="right")
        else:
            part = np.zeros(len(keys), dtype=np.int64)
        if spec.descending:
            part = (P - 1) - part
        frags = []
        for p in range(P):
            idx = np.nonzero(part == p)[0]
            if not len(idx):
                frags.append({k: np.asarray(v)[:0] for k, v in cols.items()})
                continue
            frag = _take(cols, idx)
            # pre-sort each fragment so merges are k-way merges of runs
            order = stable_argsort(frag[spec.key], spec.descending)
            frags.append(_take(frag, order))
        return frags
    rows = list(rows_of(block))
    buckets: List[List[Any]] = [[] for _ in range(P)]
    for row in rows:
        k = row[spec.key]
        p = int(np.searchsorted(boundaries, np.asarray(k), side="right")) \
            if len(boundaries) else 0
        buckets[(P - 1) - p if spec.descending else p].append(row)
    return [sorted(b, key=lambda r: r[spec.key], reverse=spec.descending)
            for b in buckets]


def _partition_repartition(block, spec: ShuffleSpec, ctx: Dict) -> List[Any]:
    """Contiguous global row ranges: partition p owns global rows
    [p*total//P, (p+1)*total//P); this block covers [offset, offset+n)."""
    P, total, offset = ctx["P"], ctx["total"], ctx["offset"]
    n = block_num_rows(block)
    frags = []
    for p in range(P):
        lo = (p * total) // P
        hi = ((p + 1) * total) // P
        start = min(max(lo - offset, 0), n)
        end = min(max(hi - offset, 0), n)
        frags.append(slice_block(block, start, end) if end > start
                     else _empty_like(block))
    return frags


def _partition_random(block, spec: ShuffleSpec, ctx: Dict) -> List[Any]:
    """Seeded row-level scatter. partition(row) depends only on (seed,
    global row index), and the merge re-sorts by global index before
    applying its seeded permutation — so the output is identical for any
    block layout of the same logical dataset."""
    P, offset, seed = ctx["P"], ctx["offset"], ctx["seed"]
    n = block_num_rows(block)
    gidx = np.arange(offset, offset + n, dtype=np.uint64)
    part = _mix64(gidx ^ _mix64(np.asarray([seed], np.uint64))[0]) \
        % np.uint64(P)
    if is_columnar(block):
        cols = dict(to_columnar(block))
        cols[_GIDX] = gidx
        return [_take(cols, np.nonzero(part == p)[0]) for p in range(P)]
    rows = list(rows_of(block))
    buckets: List[List[Any]] = [[] for _ in range(P)]
    for i, row in enumerate(rows):
        buckets[int(part[i])].append((int(gidx[i]), row))
    return buckets


def _group_rows(block, key: str, P: int) -> List[Dict[Any, List[Any]]]:
    """Hash-partitioned {group key: rows} maps, one per partition."""
    parts: List[Dict[Any, List[Any]]] = [{} for _ in range(P)]
    if is_columnar(block):
        cols = to_columnar(block)
        hashes = _hash_column(np.asarray(cols[key]))
        part = (hashes % np.uint64(P)).astype(np.int64)
        for i, row in enumerate(rows_of(cols)):
            k = row[key]
            k = k.item() if hasattr(k, "item") else k
            parts[part[i]].setdefault(k, []).append(row)
        return parts
    for row in rows_of(block):
        k = row[key]
        k = k.item() if hasattr(k, "item") else k
        parts[_hash_scalar(k) % P].setdefault(k, []).append(row)
    return parts


def _partition_groupby_agg(block, spec: ShuffleSpec, ctx: Dict) -> List[Any]:
    """Map-side combiners: each fragment is {group: [accumulator per
    agg]} — rows never cross the exchange for aggregations."""
    aggs = spec.aggs
    frags = []
    for groups in _group_rows(block, spec.key, ctx["P"]):
        frags.append({
            k: [agg.accumulate_block(agg.init(k), rows) for agg in aggs]
            for k, rows in groups.items()})
    return frags


def _partition_groupby_map(block, spec: ShuffleSpec, ctx: Dict) -> List[Any]:
    return _group_rows(block, spec.key, ctx["P"])


_PARTITIONERS = {
    "sort": _partition_sort,
    "repartition": _partition_repartition,
    "random_shuffle": _partition_random,
    "groupby_agg": _partition_groupby_agg,
    "groupby_map": _partition_groupby_map,
}


def _shuffle_map_task(block, payload):
    """One map task: partition ``block`` into P fragments; returns
    ``(*fragments, meta)`` so each fragment seals as its own object on
    this worker's local store (num_returns = P + 1)."""
    spec, ctx = payload
    env0 = _task_env()
    frags = _PARTITIONERS[spec.kind](block, spec, ctx)
    meta = {
        "bytes": [_payload_bytes(f) for f in frags],
        "frags": sum(1 for f in frags if _frag_len(f)),
        "env0": env0, "env1": _task_env(),
    }
    return tuple(frags) + (meta,)


def _frag_len(frag) -> int:
    try:
        return block_num_rows(frag)
    except Exception:
        return len(frag)


# ---------------------------------------------------------------------------
# merge side: fragments -> one output block


def _merge_two_runs(a: Dict, b: Dict, key: str) -> Dict:
    """Stable merge of two sorted columnar runs via the searchsorted
    interleave: a-rows land before equal b-rows (side=left/right pair),
    so composing pairwise merges in map order stays globally stable."""
    ak, bk = np.asarray(a[key]), np.asarray(b[key])
    a_pos = np.arange(ak.size) + np.searchsorted(bk, ak, side="left")
    b_pos = np.arange(bk.size) + np.searchsorted(ak, bk, side="right")
    n = ak.size + bk.size
    out: Dict[str, np.ndarray] = {}
    for col in a.keys():
        av, bv = np.asarray(a[col]), np.asarray(b[col])
        dtype = av.dtype if av.dtype == bv.dtype \
            else np.result_type(av, bv)
        merged = np.empty((n,) + av.shape[1:], dtype=dtype)
        merged[a_pos] = av
        merged[b_pos] = bv
        out[col] = merged
    return out


def _merge_sorted_columnar(runs: List[Dict], key: str,
                           descending: bool) -> Dict:
    if descending:
        # searchsorted needs ascending runs; a descending merge instead
        # concats in map order + one stable descending argsort — still
        # stable because concat order IS original row order
        whole = concat_blocks(runs)
        if not block_num_rows(whole):
            return runs[0]
        order = stable_argsort(np.asarray(whole[key]), descending=True)
        return _take(whole, order)
    while len(runs) > 1:
        nxt = [_merge_two_runs(runs[i], runs[i + 1], key)
               for i in range(0, len(runs) - 1, 2)]
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0]


def _merge_sort(frags: List[Any], spec: ShuffleSpec, ctx: Dict):
    import heapq

    live = [f for f in frags if _frag_len(f)]
    if not live:
        return concat_blocks([])
    if all(is_columnar(f) for f in live):
        return _merge_sorted_columnar(live, spec.key, spec.descending)
    rows_runs = [list(rows_of(f)) for f in live]
    return list(heapq.merge(*rows_runs, key=lambda r: r[spec.key],
                            reverse=spec.descending))


def _merge_random(frags: List[Any], spec: ShuffleSpec, ctx: Dict):
    seed, part = ctx["seed"], ctx["part"]
    rng = np.random.default_rng([seed, part])
    cols = [f for f in frags if isinstance(f, dict) and block_num_rows(f)]
    lists = [f for f in frags if isinstance(f, list) and f]
    for lf in lists:
        body = dict(to_columnar([r for _, r in lf]))
        body[_GIDX] = np.asarray([g for g, _ in lf], np.uint64)
        cols.append(body)
    if not cols:
        return []
    whole = concat_blocks(cols)
    # sort by global index first: makes the input to the permutation a
    # pure function of the logical dataset, not of block layout
    order = np.argsort(np.asarray(whole[_GIDX]), kind="stable")
    perm = rng.permutation(len(order))
    take = order[perm]
    if lists and len(cols) == len(lists):
        rows = sorted((r for lf in lists for r in lf), key=lambda t: t[0])
        return [rows[i][1] for i in perm]
    return {k: np.asarray(v)[take] for k, v in whole.items() if k != _GIDX}


def _merge_groupby_agg(frags: List[Any], spec: ShuffleSpec, ctx: Dict):
    aggs = spec.aggs
    merged: Dict[Any, List[Any]] = {}
    for part in frags:
        for k, accs in part.items():
            cur = merged.get(k)
            merged[k] = accs if cur is None else [
                agg.merge(a, b) for agg, a, b in zip(aggs, cur, accs)]
    keys_sorted = sorted(merged)
    block = {spec.key: np.asarray(keys_sorted)}
    for i, agg in enumerate(aggs):
        block[agg.name] = np.asarray(
            [agg.finalize(merged[k][i]) for k in keys_sorted])
    return block


def _merge_groupby_map(frags: List[Any], spec: ShuffleSpec, ctx: Dict):
    groups: Dict[Any, List[Any]] = {}
    for part in frags:
        for k, rows in part.items():
            groups.setdefault(k, []).extend(rows)
    out: List[Any] = []
    for k in sorted(groups):
        out.extend(spec.fn(groups[k]))
    return out


_MERGERS = {
    "sort": _merge_sort,
    "repartition": lambda frags, spec, ctx: concat_blocks(list(frags)),
    "random_shuffle": _merge_random,
    "groupby_agg": _merge_groupby_agg,
    "groupby_map": _merge_groupby_map,
}


def _shuffle_merge_task(payload, *frags):
    """One per-partition merge: pulls its fragments (task deps resolved
    through the bulk transfer plane) and emits (merged block, meta)."""
    spec, ctx = payload
    env0 = _task_env()
    block = _MERGERS[spec.kind](list(frags), spec, ctx)
    meta = {"rows": _frag_len(block), "bytes": _payload_bytes(block),
            "env0": env0, "env1": _task_env()}
    return block, meta


# ---------------------------------------------------------------------------
# driver-side coordinator


def _resolve_partitions(spec: ShuffleSpec, cfg, n_blocks: int,
                        total_bytes: int) -> int:
    if spec.num_partitions > 0:         # repartition pins P explicitly
        return spec.num_partitions
    if cfg.shuffle_num_partitions > 0:
        return int(cfg.shuffle_num_partitions)
    target = max(1, int(cfg.shuffle_fragment_target_bytes))
    by_bytes = min(_MAX_AUTO_PARTITIONS, -(-int(total_bytes) // target))
    if spec.kind == "random_shuffle":
        # layout-independent on purpose: P must not depend on the block
        # count or a fixed seed would shuffle differently per layout
        return max(1, by_bytes)
    return max(1, n_blocks, by_bytes)


def _spill_estimate(metas: List[Dict]) -> tuple:
    """(spill byte delta, store-went-hot flag) across every worker pid
    that ran an exchange task, from their env0/env1 snapshots."""
    per_pid: Dict[int, List[int]] = {}
    hot = False
    for m in metas:
        for env in (m.get("env0"), m.get("env1")):
            if not env:
                continue
            per_pid.setdefault(env["pid"], []).append(env["spill"])
            hot = hot or bool(env.get("hot"))
    delta = sum(max(v) - min(v) for v in per_pid.values())
    return delta, hot


def run_exchange(spec: ShuffleSpec, inputs: Iterable,
                 stats=None, stop_event: Optional[threading.Event] = None):
    """Drive one exchange: generator of merged output block refs, in
    partition order. ``inputs`` may be a live iterator — probe and
    hash-partitioned map tasks dispatch as refs arrive, overlapping with
    upstream production."""
    from .. import get, remote, wait
    from .._private.config import global_config
    from ..util.scheduling_strategies import SpreadSchedulingStrategy
    from .executor import _store_backpressure_wait

    cfg = global_config()
    stop = stop_event if stop_event is not None else threading.Event()
    met = _shuffle_metrics()

    def _submitted(n: int = 1):
        if stats is not None:
            stats.tasks_submitted += n

    seed = spec.seed
    if spec.kind == "random_shuffle":
        if seed is None:
            seed = int.from_bytes(os.urandom(8), "little")
        seed = int(seed) & (2**63 - 1)

    meta_fn = remote(num_cpus=0.25)(_exchange_meta_task)
    sample_fn = remote(num_cpus=0.25)(_exchange_sample_task)
    map_fn = remote(num_cpus=1)(_shuffle_map_task)
    merge_fn = remote(num_cpus=1,
                      scheduling_strategy=SpreadSchedulingStrategy())(
        _shuffle_merge_task)

    P: Optional[int] = None
    if spec.kind in ("groupby_agg", "groupby_map"):
        P = int(cfg.shuffle_num_partitions) or _GROUPBY_DEFAULT_PARTITIONS

    input_refs: List[Any] = []
    probe_refs: List[Any] = []
    map_rets: List[List[Any]] = []
    for ref in inputs:
        if stop.is_set():
            return
        input_refs.append(ref)
        if spec.kind == "sort":
            probe_refs.append(
                sample_fn.remote(ref, spec.key, _SAMPLES_PER_BLOCK))
            _submitted()
        elif spec.kind in ("repartition", "random_shuffle"):
            probe_refs.append(meta_fn.remote(ref))
            _submitted()
        else:
            # hash partitioning: P known up front — push fragments while
            # upstream is still producing blocks
            _store_backpressure_wait(stop)
            map_rets.append(map_fn.options(num_returns=P + 1).remote(
                ref, (spec, {"P": P})))
            _submitted()
    n_blocks = len(input_refs)
    if n_blocks == 0 or stop.is_set():
        return

    if probe_refs:
        # O(n_blocks) tuples of counts/samples — the only driver-side
        # get() over the input side of the exchange
        metas = get(probe_refs)
        nrows = [int(m[0]) for m in metas]
        total_rows = sum(nrows)
        total_bytes = sum(int(m[1]) for m in metas)
        P = _resolve_partitions(spec, cfg, n_blocks, total_bytes)
        offsets = [0]
        for n in nrows:
            offsets.append(offsets[-1] + n)
        boundaries = np.asarray([])
        if spec.kind == "sort" and P > 1:
            sampled = [np.asarray(m[2]) for m in metas if len(m[2])]
            if sampled:
                samples = np.sort(np.concatenate(sampled))
                boundaries = samples[
                    [(len(samples) * p) // P for p in range(1, P)]]
        for i, ref in enumerate(input_refs):
            if stop.is_set():
                return
            _store_backpressure_wait(stop)
            ctx: Dict[str, Any] = {"P": P}
            if spec.kind == "sort":
                ctx["boundaries"] = boundaries
            else:
                ctx.update(offset=offsets[i], total=total_rows, seed=seed)
            map_rets.append(map_fn.options(num_returns=P + 1).remote(
                ref, (spec, ctx)))
            _submitted()

    met["exchanges"].inc(tags={"op": spec.kind})

    # merge window: submit merges before earlier ones finish so their
    # fragment pulls overlap map execution; yield in partition order by
    # waiting on the head merge's (tiny) meta return
    window = max(1, int(cfg.shuffle_merge_parallelism))
    pending: "collections.deque" = collections.deque()
    merge_metas: List[Dict] = []
    next_p = 0

    def _submit_merge():
        nonlocal next_p
        p = next_p
        next_p += 1
        frag_refs = [map_rets[i][p] for i in range(n_blocks)]
        rets = merge_fn.options(num_returns=2).remote(
            (spec, {"part": p, "P": P, "seed": seed}), *frag_refs)
        _submitted()
        met["merge_tasks"].inc(tags={"op": spec.kind})
        pending.append((rets[0], rets[1]))

    while next_p < P and len(pending) < window:
        _submit_merge()
    while pending:
        if stop.is_set():
            return
        block_ref, meta_ref = pending[0]
        ready, _ = wait([meta_ref], num_returns=1, timeout=0.2)
        if not ready:
            continue
        pending.popleft()
        merge_metas.append(get(meta_ref))
        if next_p < P:
            _submit_merge()
        yield block_ref

    # metrics + out-of-core event, from O(P + n_blocks) metadata only
    try:
        map_metas = get([rets[P] for rets in map_rets]) if map_rets else []
    except Exception:
        map_metas = []
    pushed = sum(sum(m["bytes"]) for m in map_metas)
    frag_count = sum(m["frags"] for m in map_metas)
    if pushed:
        met["bytes_pushed"].inc(pushed, tags={"op": spec.kind})
    if frag_count:
        met["fragments"].inc(frag_count, tags={"op": spec.kind})
    spill_delta, hot = _spill_estimate(map_metas + merge_metas)
    if spill_delta:
        met["spill_bytes"].inc(spill_delta, tags={"op": spec.kind})
    if spill_delta or hot:
        try:
            from ..util.state import record_event

            record_event(
                f"shuffle {spec.name or spec.kind} fell back to spill "
                f"(out-of-core exchange)",
                severity="WARNING", source="DATA", op=spec.kind,
                partitions=int(P), input_blocks=n_blocks,
                spill_bytes=int(spill_delta),
                bytes_pushed=int(pushed), fragments=int(frag_count))
        except Exception:
            pass
