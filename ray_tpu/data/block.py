"""Block model: the unit of data movement (ref: python/ray/data/block.py,
_internal/arrow_block.py).

A block is either a dict of equal-length numpy arrays (columnar — the
canonical form, directly `jax.device_put`-able for the Data→HBM path) or a
plain list of rows (simple form, from from_items / flat python data).
Blocks travel between operators as ObjectRefs through the shared-memory
store; these helpers are the BlockAccessor role."""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

# a block is a dict of numpy columns (canonical), a pyarrow.Table
# (Arrow-backed columnar — zero-copy from parquet/ipc; ref:
# _internal/arrow_block.py), or a plain list of rows
Block = Union[Dict[str, np.ndarray], "pa.Table", List[Any]]


def is_arrow(block: Block) -> bool:
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        return False
    return isinstance(block, pa.Table)


def is_columnar(block: Block) -> bool:
    return isinstance(block, dict) or is_arrow(block)


def arrow_to_numpy(block: Block) -> Dict[str, np.ndarray]:
    """Arrow table -> dict-of-numpy (copy only when the layout demands,
    e.g. strings/nested; numeric columns convert zero-copy when
    contiguous)."""
    if not is_arrow(block):
        return block
    out = {}
    for name in block.schema.names:
        col = block.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            out[name] = np.asarray(col.to_pylist(), dtype=object)
    return out


def numpy_to_arrow(block: Block):
    """Dict-of-numpy -> Arrow table (for batch_format="pyarrow")."""
    import pyarrow as pa

    if is_arrow(block):
        return block
    if not isinstance(block, dict):
        raise ValueError("arrow conversion requires a columnar block")
    return pa.table({k: pa.array(np.asarray(v)) for k, v in block.items()})


def block_num_rows(block: Block) -> int:
    if is_arrow(block):
        return block.num_rows
    if is_columnar(block):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def block_size_bytes(block: Block) -> int:
    if is_arrow(block):
        return int(block.nbytes)
    if is_columnar(block):
        return int(sum(np.asarray(v).nbytes for v in block.values()))
    return int(sum(getattr(x, "nbytes", 64) for x in block))


def slice_block(block: Block, start: int, end: int) -> Block:
    if is_arrow(block):
        return block.slice(start, end - start)  # zero-copy view
    if is_columnar(block):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return []
    if is_arrow(blocks[0]):
        import pyarrow as pa

        if all(is_arrow(b) for b in blocks):
            return pa.concat_tables(blocks)  # zero-copy chunked concat
        blocks = [arrow_to_numpy(b) for b in blocks]
    elif any(is_arrow(b) for b in blocks):
        blocks = [arrow_to_numpy(b) for b in blocks]
    if is_columnar(blocks[0]):
        keys = blocks[0].keys()
        out = {}
        for k in keys:
            cols = [_np_column(b[k]) if isinstance(b[k], list)
                    else np.asarray(b[k]) for b in blocks]
            try:
                out[k] = np.concatenate(cols)
            except ValueError:
                # rectangular within each block but ragged ACROSS blocks
                # (e.g. every token list in block A is len 3, in block B
                # len 2): fall back to one object row per element
                out[k] = _np_column(
                    [row for col in cols for row in list(col)])
        return out
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out


def iter_batches(blocks: Iterator[Block], batch_size: Optional[int],
                 drop_last: bool = False) -> Iterator[Block]:
    """Re-chunk a stream of blocks into exact-size batches across block
    boundaries (ref: _internal/block_batching/). An offset cursor walks the
    buffered blocks — numpy slices are views, so only the emitted batch is
    ever copied (O(n) total, not O(n²/batch))."""
    from collections import deque

    if batch_size is None:
        yield from blocks
        return
    dq: "deque" = deque()
    head_off = 0
    buffered = 0
    for block in blocks:
        n = block_num_rows(block)
        if n:
            dq.append(block)
            buffered += n
        while buffered >= batch_size:
            need = batch_size
            parts: List[Block] = []
            while need:
                head = dq[0]
                avail = block_num_rows(head) - head_off
                take = min(avail, need)
                parts.append(slice_block(head, head_off, head_off + take))
                head_off += take
                need -= take
                if head_off == block_num_rows(head):
                    dq.popleft()
                    head_off = 0
            buffered -= batch_size
            yield parts[0] if len(parts) == 1 else concat_blocks(parts)
    if buffered and not drop_last:
        parts = []
        if dq:
            parts.append(slice_block(dq[0], head_off, block_num_rows(dq[0])))
            parts.extend(list(dq)[1:])
        yield parts[0] if len(parts) == 1 else concat_blocks(parts)


def block_schema(block: Block) -> Optional[dict]:
    if is_arrow(block):
        return {name: str(block.schema.field(name).type)
                for name in block.schema.names}
    if is_columnar(block):
        return {k: str(np.asarray(v).dtype) for k, v in block.items()}
    if block:
        return {"item": type(block[0]).__name__}
    return None


def rows_of(block: Block) -> Iterator[Any]:
    if is_arrow(block):
        yield from block.to_pylist()
        return
    if is_columnar(block):
        keys = list(block.keys())
        for i in range(block_num_rows(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block


def _np_column(values: List[Any]) -> np.ndarray:
    """Column from python values; ragged rows (e.g. variable-length token
    lists) fall back to a 1-D object array instead of raising."""
    try:
        return np.asarray(values)
    except ValueError:
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr


def to_columnar(block: Block) -> Dict[str, np.ndarray]:
    """Best-effort conversion of a simple block to columnar form."""
    if is_arrow(block):
        return arrow_to_numpy(block)
    if is_columnar(block):
        return block
    if block and isinstance(block[0], dict):
        keys = block[0].keys()
        return {k: _np_column([row[k] for row in block]) for k in keys}
    return {"item": _np_column(block)}
