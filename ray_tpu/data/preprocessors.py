"""Preprocessors: fit statistics on a Dataset, transform as map_batches
(ref: python/ray/data/preprocessors/ — scaler.py StandardScaler/
MinMaxScaler, encoder.py LabelEncoder, concatenator.py Concatenator).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted:
            raise RuntimeError(f"{type(self).__name__} is not fitted")
        fn = self._transform_batch_fn()
        return ds.map_batches(fn)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def _fit(self, ds) -> None:
        raise NotImplementedError

    def _transform_batch_fn(self):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        stats = ds.column_stats(self.columns)  # one pass for all columns
        for col in self.columns:
            self.stats_[col] = (stats[col]["mean"],
                                stats[col]["std"] or 1.0)

    def _transform_batch_fn(self):
        stats = dict(self.stats_)
        columns = list(self.columns)

        def fn(batch):
            out = dict(batch)
            for col in columns:
                mean, std = stats[col]
                out[col] = (np.asarray(batch[col], np.float64) - mean) \
                    / (std or 1.0)
            return out

        return fn


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        stats = ds.column_stats(self.columns)  # one pass for all columns
        for col in self.columns:
            self.stats_[col] = (stats[col]["min"], stats[col]["max"])

    def _transform_batch_fn(self):
        stats = dict(self.stats_)
        columns = list(self.columns)

        def fn(batch):
            out = dict(batch)
            for col in columns:
                lo, hi = stats[col]
                span = (hi - lo) or 1.0
                out[col] = (np.asarray(batch[col], np.float64) - lo) / span
            return out

        return fn


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes (sorted label order)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List = []

    def _fit(self, ds) -> None:
        seen = set()
        for row in ds.iter_rows():
            val = row[self.label_column]
            seen.add(val.item() if hasattr(val, "item") else val)
        self.classes_ = sorted(seen)

    def _transform_batch_fn(self):
        mapping = {c: i for i, c in enumerate(self.classes_)}
        col = self.label_column

        def fn(batch):
            out = dict(batch)
            out[col] = np.asarray(
                [mapping[v.item() if hasattr(v, "item") else v]
                 for v in batch[col]], np.int64)
            return out

        return fn


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (the model-
    input shape for jax training)."""

    def __init__(self, columns: List[str], output_column: str = "features",
                 drop: bool = True):
        self.columns = list(columns)
        self.output_column = output_column
        self.drop = drop

    def _fit(self, ds) -> None:
        pass

    def _transform_batch_fn(self):
        columns = list(self.columns)
        out_col = self.output_column
        drop = self.drop

        def fn(batch):
            mat = np.stack(
                [np.asarray(batch[c], np.float64) for c in columns],
                axis=1)
            out = {k: v for k, v in batch.items()
                   if not (drop and k in columns)}
            out[out_col] = mat
            return out

        return fn


__all__ = ["Preprocessor", "StandardScaler", "MinMaxScaler",
           "LabelEncoder", "Concatenator"]
