"""Datasources: how blocks enter the pipeline (ref: python/ray/data/
datasource/datasource.py — Datasource.get_read_tasks returns serializable
ReadTasks the executor schedules as remote tasks; concrete connectors in
data/_internal/datasource/)."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .block import Block


@dataclass
class ReadTask:
    """A serializable unit of reading: call `read()` inside a worker to get
    the blocks of one input shard."""

    read: Callable[[], Iterable[Block]]
    num_rows: Optional[int] = None


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimated_rows(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    """ds = range(n): integers in [0, n) as an 'id' column
    (ref: _internal/datasource/range_datasource.py)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self.n <= 0:
            return [ReadTask(lambda: iter([{"id": np.empty(0, np.int64)}]),
                             num_rows=0)]
        parallelism = max(1, min(parallelism, self.n))
        shard = -(-self.n // parallelism)
        tasks = []
        for start in range(0, self.n, shard):
            end = min(start + shard, self.n)

            def _read(start=start, end=end):
                yield {"id": np.arange(start, end, dtype=np.int64)}

            tasks.append(ReadTask(_read, num_rows=end - start))
        return tasks

    def estimated_rows(self) -> Optional[int]:
        return self.n


class ItemsDatasource(Datasource):
    """ds = from_items([...]) (ref: from_items building simple blocks)."""

    def __init__(self, items: List[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        if n == 0:
            return [ReadTask(lambda: iter([[]]), num_rows=0)]
        parallelism = max(1, min(parallelism, n))
        shard = -(-n // parallelism)
        tasks = []
        for start in range(0, n, shard):
            chunk = self.items[start: start + shard]

            def _read(chunk=chunk):
                yield list(chunk)

            tasks.append(ReadTask(_read, num_rows=len(chunk)))
        return tasks

    def estimated_rows(self) -> Optional[int]:
        return len(self.items)


def _expand_paths(paths, suffixes) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if not suffixes or any(name.endswith(s) for s in suffixes):
                    out.append(os.path.join(path, name))
        else:
            out.append(path)
    if not out:
        raise ValueError(f"no input files found under {paths}")
    return out


class ParquetDatasource(Datasource):
    """read_parquet: one read task per file, emitted as columnar blocks
    (ref: _internal/datasource/parquet_datasource.py, minus fragment-level
    splitting)."""

    def __init__(self, paths, columns: Optional[List[str]] = None,
                 batch_rows: int = 32768):
        self.files = _expand_paths(paths, (".parquet",))
        self.columns = columns
        self.batch_rows = batch_rows

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path, columns=self.columns, rows=self.batch_rows):
                import pyarrow.parquet as pq

                table = pq.read_table(path, columns=columns)
                for batch in table.to_batches(max_chunksize=rows):
                    yield {name: batch.column(i).to_numpy(zero_copy_only=False)
                           for i, name in enumerate(batch.schema.names)}

            tasks.append(ReadTask(_read))
        return tasks


class JSONLinesDatasource(Datasource):
    """read_json: newline-delimited json, one task per file."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".json", ".jsonl"))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                import json

                rows = []
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            rows.append(json.loads(line))
                yield rows

            tasks.append(ReadTask(_read))
        return tasks


class CSVDatasource(Datasource):
    """read_csv: one task per file, header row -> columnar block with
    numeric columns auto-converted (ref: _internal/datasource/
    csv_datasource.py, pyarrow-free)."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".csv",))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                import csv

                with open(path, newline="") as f:
                    reader = csv.reader(f)
                    header = next(reader, None)
                    if header is None:
                        yield []
                        return
                    cols: List[List[Any]] = [[] for _ in header]
                    for row in reader:
                        for i, val in enumerate(row):
                            cols[i].append(val)
                out = {}
                for name, col in zip(header, cols):
                    arr = np.asarray(col)
                    for dtype in (np.int64, np.float64):
                        try:
                            arr = np.asarray(col, dtype)
                            break
                        except ValueError:
                            continue
                    out[name] = arr
                yield out

            tasks.append(ReadTask(_read))
        return tasks


class NumpyDatasource(Datasource):
    """read_numpy: one .npy file per task as a 'data' column."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".npy",))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                yield {"data": np.load(path)}

            tasks.append(ReadTask(_read))
        return tasks
