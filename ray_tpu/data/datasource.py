"""Datasources: how blocks enter the pipeline (ref: python/ray/data/
datasource/datasource.py — Datasource.get_read_tasks returns serializable
ReadTasks the executor schedules as remote tasks; concrete connectors in
data/_internal/datasource/)."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .block import Block


@dataclass
class ReadTask:
    """A serializable unit of reading: call `read()` inside a worker to get
    the blocks of one input shard."""

    read: Callable[[], Iterable[Block]]
    num_rows: Optional[int] = None


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimated_rows(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    """ds = range(n): integers in [0, n) as an 'id' column
    (ref: _internal/datasource/range_datasource.py)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self.n <= 0:
            return [ReadTask(lambda: iter([{"id": np.empty(0, np.int64)}]),
                             num_rows=0)]
        parallelism = max(1, min(parallelism, self.n))
        shard = -(-self.n // parallelism)
        tasks = []
        for start in range(0, self.n, shard):
            end = min(start + shard, self.n)

            def _read(start=start, end=end):
                yield {"id": np.arange(start, end, dtype=np.int64)}

            tasks.append(ReadTask(_read, num_rows=end - start))
        return tasks

    def estimated_rows(self) -> Optional[int]:
        return self.n


class ItemsDatasource(Datasource):
    """ds = from_items([...]) (ref: from_items building simple blocks)."""

    def __init__(self, items: List[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        if n == 0:
            return [ReadTask(lambda: iter([[]]), num_rows=0)]
        parallelism = max(1, min(parallelism, n))
        shard = -(-n // parallelism)
        tasks = []
        for start in range(0, n, shard):
            chunk = self.items[start: start + shard]

            def _read(chunk=chunk):
                yield list(chunk)

            tasks.append(ReadTask(_read, num_rows=len(chunk)))
        return tasks

    def estimated_rows(self) -> Optional[int]:
        return len(self.items)


def _expand_paths(paths, suffixes) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if not suffixes or any(name.endswith(s) for s in suffixes):
                    out.append(os.path.join(path, name))
        else:
            out.append(path)
    if not out:
        raise ValueError(f"no input files found under {paths}")
    return out


class ParquetDatasource(Datasource):
    """read_parquet: one read task per file, emitted as columnar blocks
    (ref: _internal/datasource/parquet_datasource.py, minus fragment-level
    splitting)."""

    def __init__(self, paths, columns: Optional[List[str]] = None,
                 batch_rows: int = 32768, output_format: str = "numpy"):
        self.files = _expand_paths(paths, (".parquet",))
        self.columns = columns
        self.batch_rows = batch_rows
        self.output_format = output_format  # "numpy" | "arrow"

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path, columns=self.columns, rows=self.batch_rows,
                      fmt=self.output_format):
                import pyarrow.parquet as pq

                table = pq.read_table(path, columns=columns)
                if fmt == "arrow":
                    # Arrow-backed blocks end to end: slicing/batching
                    # stays zero-copy (ref: _internal/arrow_block.py)
                    for i in range(0, max(table.num_rows, 1), rows):
                        yield table.slice(i, rows)
                    return
                for batch in table.to_batches(max_chunksize=rows):
                    yield {name: batch.column(i).to_numpy(zero_copy_only=False)
                           for i, name in enumerate(batch.schema.names)}

            tasks.append(ReadTask(_read))
        return tasks


class JSONLinesDatasource(Datasource):
    """read_json: newline-delimited json, one task per file."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".json", ".jsonl"))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                import json

                rows = []
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            rows.append(json.loads(line))
                yield rows

            tasks.append(ReadTask(_read))
        return tasks


class CSVDatasource(Datasource):
    """read_csv: one task per file, header row -> columnar block with
    numeric columns auto-converted (ref: _internal/datasource/
    csv_datasource.py, pyarrow-free)."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".csv",))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                import csv

                with open(path, newline="") as f:
                    reader = csv.reader(f)
                    header = next(reader, None)
                    if header is None:
                        yield []
                        return
                    cols: List[List[Any]] = [[] for _ in header]
                    for row in reader:
                        for i, val in enumerate(row):
                            cols[i].append(val)
                out = {}
                for name, col in zip(header, cols):
                    arr = np.asarray(col)
                    for dtype in (np.int64, np.float64):
                        try:
                            arr = np.asarray(col, dtype)
                            break
                        except ValueError:
                            continue
                    out[name] = arr
                yield out

            tasks.append(ReadTask(_read))
        return tasks


class NumpyDatasource(Datasource):
    """read_numpy: one .npy file per task as a 'data' column."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".npy",))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                yield {"data": np.load(path)}

            tasks.append(ReadTask(_read))
        return tasks


class TFRecordsDatasource(Datasource):
    """read_tfrecords: TFRecord container framing + a native
    tf.train.Example wire-format parser — no tensorflow dependency
    (ref: _internal/datasource/tfrecords_datasource.py, which needs
    tf; the proto wire format is stable and tiny, so we parse it
    directly). Emits one columnar block per file: bytes features ->
    object arrays, int64/float lists -> numpy columns (scalar lists
    are flattened)."""

    def __init__(self, paths, raw: bool = False):
        self.files = _expand_paths(paths, (".tfrecord", ".tfrecords"))
        self.raw = raw  # True: yield {"data": [record bytes...]} only

    @staticmethod
    def _iter_records(path):
        import struct as _struct

        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    return
                (length,) = _struct.unpack("<Q", header)
                f.read(4)  # length crc (unchecked, like most readers)
                data = f.read(length)
                if len(data) < length:
                    return
                f.read(4)  # data crc
                yield data

    @staticmethod
    def _parse_example(buf: bytes):
        """Minimal protobuf wire parser for tf.train.Example:
        Example{1: Features{1: map<string, Feature>}},
        Feature{1: BytesList, 2: FloatList, 3: Int64List}."""
        import struct as _struct

        def varint(b, i):
            out = shift = 0
            while True:
                x = b[i]
                i += 1
                out |= (x & 0x7F) << shift
                if not x & 0x80:
                    return out, i
                shift += 7

        def fields(b):
            i = 0
            while i < len(b):
                key, i = varint(b, i)
                fno, wt = key >> 3, key & 7
                if wt == 2:
                    ln, i = varint(b, i)
                    yield fno, b[i:i + ln]
                    i += ln
                elif wt == 0:
                    v, i = varint(b, i)
                    yield fno, v
                elif wt == 5:
                    yield fno, b[i:i + 4]
                    i += 4
                elif wt == 1:
                    yield fno, b[i:i + 8]
                    i += 8
                else:
                    raise ValueError(f"unsupported wire type {wt}")

        out = {}
        for fno, features in fields(buf):          # Example.features
            if fno != 1:
                continue
            for fno2, entry in fields(features):   # Features.feature map
                if fno2 != 1:
                    continue
                name, feature = None, None
                for k, v in fields(entry):         # map entry {1:key 2:val}
                    if k == 1:
                        name = v.decode()
                    elif k == 2:
                        feature = v
                if name is None or feature is None:
                    continue
                for k, payload in fields(feature):  # Feature oneof
                    vals: List[Any]
                    if k == 1:      # BytesList{1: repeated bytes}
                        vals = [v for f2, v in fields(payload) if f2 == 1]
                    elif k == 2:    # FloatList{1: repeated float}
                        # packed (one wt-2 blob) and unpacked (wt-5
                        # 4-byte chunks) both surface as bytes: concat
                        blob = b"".join(
                            v for f2, v in fields(payload)
                            if f2 == 1 and isinstance(v, bytes))
                        vals = [float(x) for x in
                                np.frombuffer(blob, dtype="<f4")]
                    elif k == 3:    # Int64List{1: repeated int64 (packed)}
                        packed = [v for f2, v in fields(payload) if f2 == 1]
                        if packed and isinstance(packed[0], bytes):
                            ints = []
                            for blob in packed:
                                j = 0
                                while j < len(blob):
                                    val, j = varint(blob, j)
                                    ints.append(val)
                        else:
                            ints = packed
                        # two's-complement: proto int64 varints are the
                        # unsigned 64-bit image of the signed value
                        vals = [v - (1 << 64) if v >= 1 << 63 else v
                                for v in ints]
                    else:
                        continue
                    out[name] = vals
        return out

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path, raw=self.raw):
                records = list(TFRecordsDatasource._iter_records(path))
                if raw:
                    yield {"data": np.asarray(records, dtype=object)}
                    return
                parsed_rows = [TFRecordsDatasource._parse_example(rec)
                               for rec in records]
                keys = []
                for row in parsed_rows:
                    for k in row:
                        if k not in keys:
                            keys.append(k)
                # columns stay ROW-ALIGNED: a record missing a feature
                # contributes None at its row index (never a silent
                # shift pairing values with the wrong record)
                cols: Dict[str, list] = {k: [] for k in keys}
                for row in parsed_rows:
                    for k in keys:
                        vals = row.get(k)
                        if vals is None:
                            cols[k].append(None)
                        else:
                            cols[k].append(
                                vals[0] if len(vals) == 1 else vals)
                out = {}
                for k, v in cols.items():
                    try:
                        out[k] = np.asarray(v)
                    except Exception:
                        out[k] = np.asarray(v, dtype=object)
                yield out

            tasks.append(ReadTask(_read))
        return tasks


class TextDatasource(Datasource):
    """read_text: one row per line, column 'text' (ref:
    _internal/datasource/text_datasource.py)."""

    def __init__(self, paths, *, drop_empty_lines: bool = True):
        self.files = _expand_paths(paths, (".txt", ".text", ".log"))
        self.drop_empty = drop_empty_lines

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path, drop=self.drop_empty):
                with open(path, errors="replace") as f:
                    lines = [ln.rstrip("\n") for ln in f]
                if drop:
                    lines = [ln for ln in lines if ln]
                yield {"text": np.asarray(lines, dtype=object)}

            tasks.append(ReadTask(_read))
        return tasks


class BinaryDatasource(Datasource):
    """read_binary_files: whole files as rows {'bytes', 'path'} (ref:
    _internal/datasource/binary_datasource.py)."""

    def __init__(self, paths):
        self.files = _expand_paths(paths, ())

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                with open(path, "rb") as f:
                    data = f.read()
                yield {"bytes": np.asarray([data], dtype=object),
                       "path": np.asarray([path])}

            tasks.append(ReadTask(_read, num_rows=1))
        return tasks


class SQLDatasource(Datasource):
    """read_sql: any DB-API 2.0 connection (ref:
    _internal/datasource/sql_datasource.py — same contract: a
    zero-argument ``connection_factory`` so each read task opens its own
    connection in its worker process; sqlite3/psycopg/mysql all fit).
    Parallelism is 1 unless ``shard_keys`` splits the query with
    ``WHERE <key> % N = i`` (the reference's sharding option)."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 *, shard_key: Optional[str] = None, shards: int = 1):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shard_key = shard_key
        self.shards = max(1, shards)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        def make(query):
            def _read(query=query):
                conn = self.connection_factory()
                try:
                    cur = conn.cursor()
                    cur.execute(query)
                    names = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                cols: Dict[str, list] = {n: [] for n in names}
                for row in rows:
                    for name, val in zip(names, row):
                        cols[name].append(val)
                out = {}
                for name, col in cols.items():
                    try:
                        out[name] = np.asarray(col)
                    except Exception:
                        out[name] = np.asarray(col, dtype=object)
                yield out

            return _read

        if self.shard_key and self.shards > 1:
            # subquery wrap keeps the outer WHERE valid whatever the
            # user query contains; the double-mod normalizes negative
            # keys (SQL % takes the dividend's sign — plain `k % N = i`
            # would silently drop every negative-key row)
            n = self.shards
            return [ReadTask(make(
                f"SELECT * FROM ({self.sql}) __q WHERE "
                f"((__q.{self.shard_key} % {n}) + {n}) % {n} = {i}"))
                for i in range(n)]
        return [ReadTask(make(self.sql))]


class WebDatasetDatasource(Datasource):
    """read_webdataset: tar shards of samples grouped by key — members
    ``<key>.<ext>`` form one row with one column per extension (ref:
    _internal/datasource/webdataset_datasource.py, tarfile-native here).
    Text-ish extensions decode to str, ``.json`` parses, the rest stay
    bytes."""

    TEXT_EXTS = ("txt", "text", "cls", "caption")

    def __init__(self, paths):
        self.files = _expand_paths(paths, (".tar",))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path):
                import json
                import tarfile

                samples: Dict[str, Dict[str, Any]] = {}
                order: List[str] = []
                with tarfile.open(path) as tf:
                    for member in tf:
                        if not member.isfile():
                            continue
                        # webdataset convention: the key is the full
                        # path up to the basename's first dot — samples
                        # in different subdirs must not merge
                        dirn = os.path.dirname(member.name)
                        stem, _, ext = os.path.basename(
                            member.name).partition(".")
                        key = f"{dirn}/{stem}" if dirn else stem
                        data = tf.extractfile(member).read()
                        if ext in self.TEXT_EXTS:
                            value: Any = data.decode(errors="replace")
                        elif ext == "json":
                            value = json.loads(data)
                        else:
                            value = data
                        if key not in samples:
                            samples[key] = {"__key__": key}
                            order.append(key)
                        samples[key][ext] = value
                yield [samples[k] for k in order]

            tasks.append(ReadTask(_read))
        return tasks


class ImageDatasource(Datasource):
    """read_images: one task per file; blocks carry {"image": HWC uint8,
    "path": str} (ref: _internal/datasource/image_datasource.py, PIL-
    backed). ``size=(H, W)`` resizes at read time so downstream blocks
    are uniform and stackable."""

    EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths, size: Optional[tuple] = None,
                 mode: str = "RGB"):
        self.files = _expand_paths(paths, self.EXTS)
        self.size = size
        self.mode = mode

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self.files:
            def _read(path=path, size=self.size, mode=self.mode):
                from PIL import Image

                img = Image.open(path).convert(mode)
                if size is not None:
                    img = img.resize((size[1], size[0]))
                arr = np.asarray(img)
                yield {"image": arr[None, ...],
                       "path": np.asarray([path])}

            tasks.append(ReadTask(_read))
        return tasks
