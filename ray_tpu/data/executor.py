"""Streaming execution of dataset plans.

Reference analog: data/_internal/execution/streaming_executor.py:48 (+
streaming_executor_state.py select_operator_to_run/process_completed_tasks,
operators/ task pools, output_splitter.py). Re-shaped for this runtime: each
physical operator is a pipeline stage thread connected by bounded queues —
the queue bound IS the backpressure policy (a slow consumer stalls the whole
chain without buffering the dataset in memory), and per-stage in-flight task
caps bound cluster resource use. Reads ride streaming generators so a large
file's blocks flow before the file finishes reading.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_SENTINEL = "__stream_end__"

# stage tuning (ref: backpressure_policy/ + resource_manager defaults)
MAX_INFLIGHT_PER_STAGE = 4
AUTOSCALE_MAX_INFLIGHT = 12   # per-op autoscaler growth ceiling
STAGE_QUEUE_CAP = 8


def _store_backpressure_wait(stop_event: "threading.Event",
                             max_wait_s: float = 5.0) -> None:
    """Pause dispatch while the local object store sits above the
    spilling threshold (ref: _internal/execution/resource_manager.py +
    backpressure_policy/ConcurrencyCapBackpressurePolicy — here the
    signal is actual store usage, not a static cap). Bounded: with
    disk spilling behind the store this is congestion control, not a
    correctness gate, so a store pinned full by foreign objects must
    not deadlock the pipeline."""
    from .._worker_api import _core
    from .._private.config import global_config

    core = _core
    if core is None:
        return
    threshold = global_config().object_spilling_threshold
    capacity = core.store.capacity or 1
    waited = 0.0
    while not stop_event.is_set() and waited < max_wait_s:
        try:
            if core.store.used_bytes() / capacity < threshold:
                return
        except Exception:
            return
        time.sleep(0.05)
        waited += 0.05


@dataclass
class StageStats:
    name: str
    blocks_out: int = 0
    tasks_submitted: int = 0
    # final in-flight cap (> the default when the autoscaler engaged)
    max_inflight: int = 0


class _Stage(threading.Thread):
    """One physical operator: consume refs from in_q, produce refs to out_q.
    ``stop_event`` is the downstream-satisfied signal (a reached limit):
    stages stop dispatching and drop inputs once it fires."""

    def __init__(self, name: str, out_q: "queue.Queue",
                 in_q: Optional["queue.Queue"] = None):
        super().__init__(daemon=True, name=f"data_stage_{name}")
        self.stage_name = name
        self.in_q = in_q
        self.out_q = out_q
        self.stats = StageStats(name)
        self.error: Optional[BaseException] = None
        self.stop_event = threading.Event()

    def run(self):
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 — surfaced by the executor
            self.error = e
        finally:
            # the sentinel must be delivered or the downstream stage
            # polls its input forever — a SLOW consumer (full queue for
            # >1s on a loaded box) is not an abandoned one. Always TRY
            # (a stopped stage's consumer may be the limit's post-stop
            # drain loop, which needs the eof to finish) and give up
            # only when stopped AND the queue stays full (the consumer
            # is truly gone).
            while True:
                try:
                    self.out_q.put(_SENTINEL, timeout=0.2)
                    break
                except queue.Full:
                    if self.stop_event.is_set():
                        break

    def _put_out(self, item) -> bool:
        """Bounded, stop-aware put: returns False (dropping the item) once
        the stream is being torn down, so no stage thread can block forever
        on a queue whose consumer is gone."""
        while True:
            if self.stop_event.is_set():
                return False
            try:
                self.out_q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue

    def _run(self):
        raise NotImplementedError


class ReadStage(_Stage):
    """Dispatch ReadTasks as streaming-generator remote tasks; drain each
    generator on a small thread so multiple files read concurrently
    (ref: operators/input_data_buffer.py + read task scheduling)."""

    def __init__(self, read_tasks: List[Any], out_q, ray_remote_args: dict):
        super().__init__("read", out_q)
        self.read_tasks = read_tasks
        self.ray_remote_args = ray_remote_args

    def _run(self):
        import cloudpickle

        from .. import remote

        @remote(num_returns="streaming", **self.ray_remote_args)
        def _exec_read(task_blob):
            task = cloudpickle.loads(task_blob)
            for block in task.read():
                yield block

        # Reads run concurrently (bounded), but blocks are EMITTED in read
        # task order — the stream is ordered, which is what makes
        # take()/limit() deterministic (ref: preserve_order execution).
        slots = threading.Semaphore(MAX_INFLIGHT_PER_STAGE)
        task_done = "__task_done__"
        buffers: List["queue.Queue"] = []

        def _drain(gen, buf):
            try:
                for ref in gen:
                    if self.stop_event.is_set():
                        from .. import cancel

                        cancel(gen)
                        break
                    while not self.stop_event.is_set():
                        try:
                            buf.put(ref, timeout=0.2)
                            break
                        except queue.Full:
                            continue
            finally:
                try:
                    buf.put(task_done, timeout=1.0)
                except queue.Full:
                    pass
                slots.release()

        def _launch_all():
            for task in self.read_tasks:
                if self.stop_event.is_set():
                    break  # downstream satisfied (limit reached)
                slots.acquire()
                _store_backpressure_wait(self.stop_event)
                buf: "queue.Queue" = queue.Queue(maxsize=STAGE_QUEUE_CAP)
                buffers.append(buf)
                gen = _exec_read.remote(cloudpickle.dumps(task))
                self.stats.tasks_submitted += 1
                threading.Thread(target=_drain, args=(gen, buf),
                                 daemon=True).start()
            buffers.append(None)  # end of tasks

        threading.Thread(target=_launch_all, daemon=True).start()
        import time as _time

        i = 0
        while True:
            while len(buffers) <= i:
                _time.sleep(0.01)
            buf = buffers[i]
            if buf is None:
                return
            while True:
                try:
                    item = buf.get(timeout=0.5)
                except queue.Empty:
                    if self.stop_event.is_set():
                        return
                    continue
                if item is task_done:
                    break
                if self._put_out(item):
                    self.stats.blocks_out += 1
            i += 1


class RefsStage(_Stage):
    """Source stage over pre-materialized block refs (ref:
    operators/input_data_buffer.py)."""

    def __init__(self, refs: List[Any], out_q):
        super().__init__("refs", out_q)
        self.refs = refs

    def _run(self):
        for ref in self.refs:
            if not self._put_out(ref):
                return
            self.stats.blocks_out += 1


class MapStage(_Stage):
    """One remote task per input block, emitted in input order so the block
    stream stays ordered end-to-end (ref: task_pool_map_operator.py with
    preserve_order). Up to MAX_INFLIGHT tasks run concurrently; only
    emission is head-of-line."""

    def __init__(self, name: str, in_q, out_q, block_fn: Callable,
                 ray_remote_args: dict, budget: Optional[dict] = None):
        super().__init__(name, out_q, in_q)
        self.block_fn = block_fn
        self.ray_remote_args = ray_remote_args
        budget = budget or {}
        self.max_inflight = budget.get("max_inflight",
                                       MAX_INFLIGHT_PER_STAGE)
        self.memory_budget = budget.get("memory_budget_bytes")
        # per-operator autoscaler (ref: data/_internal/execution/
        # autoscaler/ — the reference sizes each operator's pool from
        # observed pressure): when this op is the bottleneck (inputs
        # waiting AND the task pool saturated) its in-flight cap grows,
        # up to `autoscale_max`; sustained idleness decays it back.
        # An explicit max_inflight budget pins the cap (user override).
        self.autoscale_max = (0 if "max_inflight" in budget
                              else budget.get("autoscale_max",
                                              AUTOSCALE_MAX_INFLIGHT))
        if "max_inflight" in budget and "autoscale_max" in budget:
            raise ValueError(
                "max_inflight pins the cap; it cannot be combined "
                "with autoscale_max")
        if self.autoscale_max and self.autoscale_max < self.max_inflight:
            # a ceiling below the starting cap IS the cap
            self.max_inflight = self.autoscale_max
        self._pressure = 0
        self._idle_polls = 0
        self.stats.max_inflight = self.max_inflight

    def _autoscale(self, queue_had_item: bool, pool_full: bool) -> None:
        if not self.autoscale_max:
            return
        if queue_had_item and pool_full:
            self._pressure += 1
            self._idle_polls = 0
            if (self._pressure >= 2
                    and self.max_inflight < self.autoscale_max):
                self.max_inflight += 1
                self.stats.max_inflight = max(self.stats.max_inflight,
                                              self.max_inflight)
                self._pressure = 0
        elif pool_full:
            # saturated with a momentarily empty queue is BUSY, not
            # idle — counting it would oscillate the cap on bursty
            # upstream delivery
            pass
        elif not queue_had_item:
            self._idle_polls += 1
            if (self._idle_polls >= 16
                    and self.max_inflight > MAX_INFLIGHT_PER_STAGE):
                self.max_inflight -= 1
                self._idle_polls = 0

    @staticmethod
    def _ref_size(item) -> int:
        """Plasma size of an input block ref (0 when unknowable) — the
        basis for the per-operator memory budget. Uses the no-touch
        store.size(): mapping (or restoring a spilled block) just to
        read its length would re-create the pressure the budget caps."""
        try:
            from .._worker_api import _core

            if _core is None or not hasattr(item, "id"):
                return 0
            size = _core.store.size(item.id())
            if size:
                return size
            data = _core.memory_store.get(item.id())
            return len(data) if data is not None else 0
        except Exception:
            return 0

    def _run(self):
        import collections

        from .. import remote, wait

        map_task = remote(**self.ray_remote_args)(self.block_fn)
        inflight: "collections.deque" = collections.deque()
        inflight_bytes = 0
        eof = False
        while True:
            # keep the task pool full; every wait is bounded so stop_event
            # (limit satisfied, stream torn down) always terminates the
            # stage — a stage thread must never outlive its executor
            while not eof and len(inflight) < self.max_inflight:
                try:
                    item = self.in_q.get(timeout=0.2)
                except queue.Empty:
                    self._autoscale(False, False)
                    if self.stop_event.is_set() and not inflight:
                        return
                    break
                if item is _SENTINEL:
                    eof = True
                    break
                if self.stop_event.is_set():
                    continue  # downstream satisfied: drop, don't dispatch
                _store_backpressure_wait(self.stop_event)
                size = 0
                if self.memory_budget is not None:
                    size = self._ref_size(item)
                    # the operator's in-flight input bytes stay under
                    # budget; a lone over-budget block still dispatches
                    # so a big block can't wedge the stream
                    while (inflight
                           and inflight_bytes + size > self.memory_budget
                           and not self.stop_event.is_set()):
                        done, _ = wait([inflight[0][0]], num_returns=1,
                                       timeout=0.2)
                        if done:
                            ref, sz = inflight.popleft()
                            inflight_bytes -= sz
                            if self._put_out(ref):
                                self.stats.blocks_out += 1
                inflight.append((map_task.remote(item), size))
                inflight_bytes += size
                self.stats.tasks_submitted += 1
            if not inflight:
                if eof:
                    return
                continue
            if not eof and len(inflight) >= self.max_inflight:
                # saturated right after refill with input still waiting:
                # this op is the bottleneck — the autoscaler grow signal
                # (checked HERE, post-fill, because the pop at the end of
                # each cycle means the top of the loop is never
                # saturated). The end-of-stream sentinel is not input:
                # it must not grow the cap when nothing is dispatchable.
                try:
                    head_item = self.in_q.queue[0]  # racy peek, read-only
                except IndexError:
                    head_item = None
                self._autoscale(
                    head_item is not None and head_item is not _SENTINEL,
                    True)
            head = inflight[0][0]
            ready, _ = wait([head], num_returns=1, timeout=0.2)
            if ready:
                ref, size = inflight.popleft()
                inflight_bytes -= size
                if self._put_out(ref):
                    self.stats.blocks_out += 1


class ShuffleExchangeStage(_Stage):
    """Push-based map/merge all-to-all exchange (shuffle.py): map tasks
    partition each input block into P fragments sealed on their local
    store; spread-scheduled per-partition merge tasks pull their
    fragments through the bulk transfer plane and emit the merged
    output blocks. The driver holds only refs and O(P) metadata — rows
    never land in driver memory — and fragments spill/restore through
    the parallel spill I/O plane when the working set outgrows the
    store (ref: _internal/planner/exchange/ physical operators;
    Exoshuffle 2023 + Magnet VLDB'20 push-based merging). Input refs
    stream straight from in_q into the exchange, so probe tasks and
    hash-partitioned map fragments overlap upstream production."""

    def __init__(self, name: str, in_q, out_q, spec):
        super().__init__(name, out_q, in_q)
        self.spec = spec

    def _iter_inputs(self):
        while True:
            try:
                item = self.in_q.get(timeout=0.5)
            except queue.Empty:
                if self.stop_event.is_set():
                    return
                continue
            if item is _SENTINEL:
                return
            yield item

    def _run(self):
        from .shuffle import run_exchange

        for ref in run_exchange(self.spec, self._iter_inputs(),
                                stats=self.stats,
                                stop_event=self.stop_event):
            if not self._put_out(ref):
                return
            self.stats.blocks_out += 1


class AllToAllStage(_Stage):
    """Generic barrier stage: gather every upstream block ref, hand the
    full list to ``fn(refs) -> iterable of refs``. The built-in
    all-to-all ops (sort/repartition/random_shuffle/groupby) run on
    ShuffleExchangeStage; this remains the escape hatch for
    user-supplied exchange functions."""

    def __init__(self, name: str, in_q, out_q, fn: Callable):
        super().__init__(name, out_q, in_q)
        self.fn = fn

    def _run(self):
        refs = []
        while True:
            try:
                item = self.in_q.get(timeout=0.5)
            except queue.Empty:
                if self.stop_event.is_set():
                    return
                continue
            if item is _SENTINEL:
                break
            refs.append(item)
        for out in self.fn(refs):
            if not self._put_out(out):
                return
            self.stats.blocks_out += 1


class LimitStage(_Stage):
    """Truncate the stream to n rows (ref: operators/limit_operator.py).
    Row counts come from tiny metadata tasks so blocks stay remote."""

    def __init__(self, in_q, out_q, limit: int, ray_remote_args: dict):
        super().__init__("limit", out_q, in_q)
        self.limit = limit
        self.ray_remote_args = ray_remote_args
        self.upstream: List[_Stage] = []  # wired by build_executor

    def _run(self):
        from .. import get, remote

        from .block import block_num_rows, slice_block

        @remote(**self.ray_remote_args)
        def _nrows(block):
            return block_num_rows(block)

        @remote(**self.ray_remote_args)
        def _head(block, n):
            return slice_block(block, 0, n)

        taken = 0
        while taken < self.limit:
            try:
                item = self.in_q.get(timeout=0.5)
            except queue.Empty:
                if self.stop_event.is_set():
                    return
                continue
            if item is _SENTINEL:
                return
            rows = get(_nrows.remote(item))
            if taken + rows <= self.limit:
                if not self._put_out(item):
                    return
                taken += rows
            else:
                if not self._put_out(_head.remote(item, self.limit - taken)):
                    return
                taken = self.limit
            self.stats.blocks_out += 1
        # limit satisfied: tell upstream stages to stop dispatching/reading,
        # then drain (and drop) what's already in flight
        for stage in self.upstream:
            stage.stop_event.set()
        while True:
            try:
                if self.in_q.get(timeout=0.5) is _SENTINEL:
                    return
            except queue.Empty:
                if self.stop_event.is_set():
                    return


class StreamingExecutor:
    """Run a chain of stages, exposing the final bounded queue."""

    def __init__(self, stages: List[_Stage], out_q: "queue.Queue"):
        self.stages = stages
        self.out_q = out_q
        self._started = False

    def start(self):
        if not self._started:
            self._started = True
            for stage in self.stages:
                stage.start()

    def iter_output(self):
        """Yield block refs; raises the first stage error at stream end.
        On exit (clean, error, or abandoned generator) every stage is told
        to stop so no thread outlives the execution."""
        self.start()
        try:
            while True:
                item = self.out_q.get()
                if item is _SENTINEL:
                    break
                yield item
            for stage in self.stages:
                if stage.error is not None:
                    raise stage.error
        finally:
            for stage in self.stages:
                stage.stop_event.set()

    def stats(self) -> List[StageStats]:
        return [s.stats for s in self.stages]


def _fuse_map_ops(plan):
    """Operator fusion (ref: _internal/logical/optimizers — MapFusion):
    consecutive map_block ops with identical remote args collapse into
    one stage, so a map->filter->map chain costs one task per block
    instead of three hops through the object store."""
    from .dataset import _LogicalOp

    fused = [plan[0]]
    for op in plan[1:]:
        prev = fused[-1]
        if (op.kind == "map_block" and prev.kind == "map_block"
                and op.remote_args == prev.remote_args
                and op.budget == prev.budget):
            first_fn = prev.args["block_fn"]
            second_fn = op.args["block_fn"]

            def chained(block, _f=first_fn, _s=second_fn):
                return _s(_f(block))

            fused[-1] = _LogicalOp(
                "map_block", f"{prev.name}->{op.name}",
                {"block_fn": chained}, prev.remote_args, prev.budget)
        else:
            fused.append(op)
    return fused


def _pushdown_projection(plan):
    """Logical optimization: a select_columns immediately after a
    column-aware read moves INTO the read (ref: _internal/logical/
    optimizers.py projection pushdown) — parquet then never
    materializes the dropped columns at all. The plan visibly loses the
    select op (asserted by tests/test_data_optimizer.py)."""
    if len(plan) < 2 or plan[0].kind != "read":
        return plan
    op = plan[1]
    cols = op.args.get("columns") if op.kind == "map_block" else None
    src = plan[0].args.get("datasource")
    if cols is None or not hasattr(src, "columns"):
        return plan
    import copy

    new_src = copy.copy(src)
    new_src.columns = (list(cols) if new_src.columns is None
                      else [c for c in new_src.columns if c in cols])
    read = type(plan[0])(plan[0].kind,
                         plan[0].name + f"[cols={','.join(cols)}]",
                         dict(plan[0].args, datasource=new_src),
                         plan[0].remote_args)
    return [read] + plan[2:]


def optimize_plan(plan):
    """All logical-plan rewrites, in order (the reference's logical
    optimizer chain, ref: _internal/logical/optimizers.py): projection
    pushdown into reads, then adjacent-map fusion."""
    plan = _pushdown_projection(plan)
    return _fuse_map_ops(plan)


def build_executor(plan, parallelism: int) -> StreamingExecutor:
    """Logical plan → stage chain (the planner role, ref:
    _internal/planner/)."""
    plan = optimize_plan(plan)
    stages: List[_Stage] = []
    q: "queue.Queue" = queue.Queue(maxsize=STAGE_QUEUE_CAP)
    first = plan[0]
    if first.kind == "read":
        read_tasks = first.args["datasource"].get_read_tasks(parallelism)
        stages.append(ReadStage(read_tasks, q, first.remote_args))
    elif first.kind == "refs":
        stages.append(RefsStage(first.args["refs"], q))
    else:
        raise ValueError(f"plan must start with read/refs, got {first.kind}")
    for op in plan[1:]:
        next_q: "queue.Queue" = queue.Queue(maxsize=STAGE_QUEUE_CAP)
        if op.kind == "map_block":
            stages.append(MapStage(op.name, q, next_q, op.args["block_fn"],
                                   op.remote_args, op.budget))
        elif op.kind == "shuffle_exchange":
            stages.append(ShuffleExchangeStage(op.name, q, next_q,
                                               op.args["spec"]))
        elif op.kind == "all_to_all":
            stages.append(AllToAllStage(op.name, q, next_q,
                                        op.args["fn"]))
        elif op.kind == "limit":
            limit_stage = LimitStage(q, next_q, op.args["n"], op.remote_args)
            limit_stage.upstream = list(stages)
            stages.append(limit_stage)
        else:
            raise ValueError(f"unknown physical op {op.kind}")
        q = next_q
    return StreamingExecutor(stages, q)


class SplitCoordinator:
    """Actor fanning one executed stream into n consumer queues
    (ref: dataset.py:1606 streaming_split → _internal/execution/operators/
    output_splitter.py + the StreamSplitDataIterator coordinator actor).
    Round-robin dispatch; every consumer sees a near-equal share. Runs as
    an actor so train workers on any node can pull their split."""

    def __init__(self, plan_blob: bytes, parallelism: int, n: int):
        import cloudpickle

        self.plan = cloudpickle.loads(plan_blob)
        self.parallelism = parallelism
        self.n = n
        self.queues = [queue.Queue(maxsize=STAGE_QUEUE_CAP) for _ in range(n)]
        self._pump: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._drained: set = set()
        self._released: set = set()   # splits whose consumer gave up

    def _ensure_started(self):
        with self._lock:
            if self._pump is not None:
                return
            executor = build_executor(self.plan, self.parallelism)

            def pump():
                i = 0
                try:
                    for ref in executor.iter_output():
                        # bounded put, re-checking for released splits: a
                        # consumer that stopped pulling (early epoch end,
                        # dead worker whose iterator was released) must not
                        # wedge every other split behind its full queue
                        if len(self._released) == self.n:
                            return  # every consumer gone: stop executing
                        while True:
                            split = i % self.n
                            if split in self._released:
                                i += 1  # drop this split's share
                                continue
                            try:
                                self.queues[split].put(ref, timeout=1.0)
                                break
                            except queue.Full:
                                continue
                        i += 1
                finally:
                    for q in self.queues:
                        q.put(_SENTINEL)

            self._pump = threading.Thread(target=pump, daemon=True,
                                          name="split_pump")
            self._pump.start()

    def release_split(self, split: int) -> bool:
        """Consumer gave up on this split (iterator closed): stop feeding
        it so its full queue cannot wedge the other splits."""
        self._released.add(split)
        with self._lock:
            self._drained.add(split)
            if len(self._drained) == self.n:
                import os
                import threading as _t

                _t.Timer(0.5, lambda: os._exit(0)).start()
        # unblock a pump stuck on this queue right now
        try:
            self.queues[split].get_nowait()
        except queue.Empty:
            pass
        return True

    def next_block(self, split: int):
        """The next block for this split (as a value — the actor-task
        return is owned by the caller, so it cannot be freed out from
        under a prefetching consumer), or the end sentinel."""
        from .. import get

        self._ensure_started()
        item = self.queues[split].get()
        if isinstance(item, str) and item == _SENTINEL:
            with self._lock:
                self._drained.add(split)
                if len(self._drained) == self.n:
                    # every consumer saw end-of-stream: release this actor's
                    # worker + resources instead of idling forever
                    import os
                    import threading as _t

                    _t.Timer(0.5, lambda: os._exit(0)).start()
            return _SENTINEL
        return get(item)
