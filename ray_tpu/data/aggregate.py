"""User-defined aggregations (ref: python/ray/data/aggregate.py
AggregateFn + the built-in Count/Sum/Min/Max/Mean/Std/AbsMax family,
driven by GroupedData.aggregate at grouped_data.py:49).

An AggregateFn is the classic fold triple: `init(key)` makes an
accumulator, `accumulate_block(acc, rows)` folds one block's rows of a
group into it, `merge(a, b)` combines accumulators from different
blocks, `finalize(acc)` produces the output value. Per-block
accumulation runs as remote tasks (one per input block), so only
accumulator-sized state — not rows — crosses the exchange.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

__all__ = ["AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std",
           "AbsMax"]


class AggregateFn:
    def __init__(self, *,
                 init: Callable[[Any], Any],
                 merge: Callable[[Any, Any], Any],
                 accumulate_row: Optional[Callable[[Any, dict], Any]] = None,
                 accumulate_block: Optional[Callable[[Any, List[dict]], Any]] = None,
                 finalize: Optional[Callable[[Any], Any]] = None,
                 name: str = "agg"):
        if accumulate_row is None and accumulate_block is None:
            raise ValueError(
                "provide accumulate_row or accumulate_block")
        if accumulate_block is None:
            def accumulate_block(acc, rows,
                                 _row_fn=accumulate_row):
                for row in rows:
                    acc = _row_fn(acc, row)
                return acc
        self.init = init
        self.merge = merge
        self.accumulate_block = accumulate_block
        self.finalize = finalize or (lambda acc: acc)
        self.name = name


def Count() -> AggregateFn:
    return AggregateFn(
        init=lambda k: 0,
        accumulate_block=lambda acc, rows: acc + len(rows),
        merge=lambda a, b: a + b,
        name="count()")


def _np_fold(value_key: str, np_fn, merge, name, finalize=None,
             empty=None) -> AggregateFn:
    import numpy as np

    def accumulate_block(acc, rows):
        vals = np.asarray([row[value_key] for row in rows])
        part = np_fn(vals) if len(vals) else empty
        if part is None:
            return acc
        return part if acc is None else merge(acc, part)

    return AggregateFn(
        init=lambda k: None,
        accumulate_block=accumulate_block,
        merge=lambda a, b: (b if a is None else a if b is None
                            else merge(a, b)),
        finalize=finalize or (lambda acc: acc),
        name=f"{name}({value_key})")


def Sum(on: str) -> AggregateFn:
    import numpy as np

    return _np_fold(on, np.sum, lambda a, b: a + b, "sum")


def Min(on: str) -> AggregateFn:
    import numpy as np

    return _np_fold(on, np.min, min, "min")


def Max(on: str) -> AggregateFn:
    import numpy as np

    return _np_fold(on, np.max, max, "max")


def AbsMax(on: str) -> AggregateFn:
    import numpy as np

    return _np_fold(on, lambda v: np.max(np.abs(v)), max, "abs_max")


def Mean(on: str) -> AggregateFn:
    import numpy as np

    def accumulate_block(acc, rows):
        vals = np.asarray([row[on] for row in rows], np.float64)
        return (acc[0] + vals.sum(), acc[1] + len(vals))

    return AggregateFn(
        init=lambda k: (0.0, 0),
        accumulate_block=accumulate_block,
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda acc: acc[0] / acc[1] if acc[1] else float("nan"),
        name=f"mean({on})")


def Std(on: str, ddof: int = 0) -> AggregateFn:
    """Merged via count/sum/sum-of-squares so block accumulators
    combine exactly."""
    import numpy as np

    def accumulate_block(acc, rows):
        vals = np.asarray([row[on] for row in rows], np.float64)
        return (acc[0] + len(vals), acc[1] + vals.sum(),
                acc[2] + np.square(vals).sum())

    def finalize(acc):
        n, s, ss = acc
        if n - ddof <= 0:
            return float("nan")
        var = (ss - s * s / n) / (n - ddof)
        return float(np.sqrt(max(var, 0.0)))

    return AggregateFn(
        init=lambda k: (0, 0.0, 0.0),
        accumulate_block=accumulate_block,
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
        finalize=finalize,
        name=f"std({on})")
