"""ray_tpu.dag: DAG IR + compiled graphs (aDAG) — ref: python/ray/dag/.

Build with ``actor.method.bind(...)`` under an ``InputNode`` context;
``.execute()`` runs interpreted (normal actor tasks);
``.experimental_compile()`` returns a CompiledDAG whose actors run
standing channel-fed loops (SURVEY §2.4 Compiled Graphs)."""

from .compiled import CompiledDAG, CompiledDAGRef
from .nodes import (
    AttributeNode,
    ClassMethodNode,
    ClassNode,
    CollectiveNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    collective,
)

__all__ = [
    "DAGNode", "InputNode", "InputAttributeNode", "AttributeNode",
    "ClassMethodNode", "ClassNode", "FunctionNode", "MultiOutputNode",
    "CollectiveNode", "collective", "CompiledDAG", "CompiledDAGRef",
]
