"""DAG IR (ref: python/ray/dag/ — dag_node.py, input_node.py,
class_node.py, output_node.py). Nodes are built with ``.bind`` on actor
methods, executed either interpreted (normal actor tasks, dependencies as
ObjectRefs) or compiled (ray_tpu/dag/compiled.py — channel loops)."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: something that produces a value per DAG execution."""

    # set by with_device_transport(): this node's output edge moves as
    # device tensors over the PJRT transfer fabric in compiled DAGs
    device_transport: bool = False

    def with_device_transport(self) -> "DAGNode":
        """Mark this node's output for device-to-device transport (ref:
        with_tensor_transport / TorchTensorType hints — the TPU analog
        rides experimental.DeviceChannel). Compiled DAGs then move this
        edge's jax arrays peer-to-peer through the transfer fabric
        instead of the host-shm lane. Requires exactly one remote
        consumer and no driver read of this node."""
        if isinstance(self, (AttributeNode, InputAttributeNode,
                             MultiOutputNode)):
            # the compiler checks the flag on the PRODUCER node; letting
            # a wrapper carry it would silently ride the shm lane
            raise TypeError(
                "with_device_transport() applies to the producing node "
                "— call it on the .bind(...) result before indexing/"
                "wrapping")
        if isinstance(self, InputNode):
            # the DRIVER writes the input edge; it feeds host values, so
            # a device channel there fails at the first execute()
            raise TypeError(
                "with_device_transport() cannot apply to the InputNode "
                "(the driver writes that edge with host values)")
        self.device_transport = True
        return self

    def experimental_compile(self, *, buffer_size_bytes: int = 1 << 20,
                             max_inflight: int = 2):
        from .compiled import CompiledDAG

        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes,
                           max_inflight=max_inflight)

    def execute(self, *args, **kwargs):
        """Interpreted execution: one actor task per node, dependencies
        passed as ObjectRefs (ref: dag_node.py execute)."""
        cache: Dict[int, Any] = {}
        return _exec_interpreted(self, args, kwargs, cache)

    # composition sugar
    def __getitem__(self, key):
        return AttributeNode(self, key)


class InputNode(DAGNode):
    """The DAG's per-execution input (ref: input_node.py). Use as a
    context manager:  with InputNode() as inp: dag = a.f.bind(inp)"""

    _local = threading.local()

    def __enter__(self):
        stack = getattr(InputNode._local, "stack", None)
        if stack is None:
            stack = InputNode._local.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        InputNode._local.stack.pop()

    def __getitem__(self, key):
        return InputAttributeNode(self, key)


class InputAttributeNode(DAGNode):
    """inp[0] / inp["key"]: positional or keyword piece of the input."""

    def __init__(self, input_node: InputNode, key):
        self.input_node = input_node
        self.key = key


class AttributeNode(DAGNode):
    """node[key]: index into an upstream node's result."""

    def __init__(self, upstream: DAGNode, key):
        self.upstream = upstream
        self.key = key


class ClassMethodNode(DAGNode):
    """actor.method.bind(*args) (ref: class_node.py ClassMethodNode)."""

    def __init__(self, handle, method_name: str, args: tuple,
                 kwargs: dict, options: dict):
        self.handle = handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.options = options


class FunctionNode(DAGNode):
    """remote_fn.bind(*args) (ref: function_node.py FunctionNode) —
    interpreted/workflow execution only (compiled DAGs are actor
    pipelines)."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs


class ClassNode(DAGNode):
    """ActorClass.bind(...): lazily-created actor in a DAG
    (ref: class_node.py ClassNode). Interpreted-only convenience: the
    actor is created on first execute."""

    def __init__(self, actor_cls, args: tuple, kwargs: dict, options: dict):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs
        self._handle = None

    def _resolve(self):
        if self._handle is None:
            self._handle = self.actor_cls.remote(*self.args, **self.kwargs)
        return self._handle

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        node = self

        class _BoundMethod:
            def bind(self, *args, **kwargs):
                handle = node._resolve()
                return ClassMethodNode(handle, name, args, kwargs, {})

        return _BoundMethod()


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one DAG output (ref: output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)


class CollectiveNode(DAGNode):
    """One participant's output of a cross-actor collective
    (ref: dag/collective_node.py). Built via dag.collective.allreduce."""

    def __init__(self, group: "_CollectiveGroup", index: int):
        self.group = group
        self.index = index


class _CollectiveGroup:
    def __init__(self, inputs: List[DAGNode], op: str):
        for n in inputs:
            if not isinstance(n, ClassMethodNode):
                raise TypeError(
                    "collective inputs must be actor-method nodes")
        self.inputs = inputs
        self.op = op
        self.nodes = [CollectiveNode(self, i) for i in range(len(inputs))]


class _AllReduce:
    def bind(self, inputs: List[DAGNode], op: str = "sum"):
        """allreduce.bind([n1, n2, ...]) -> [r1, r2, ...] where every ri
        is the elementwise reduction of all inputs, living on ni's actor
        (ref: experimental/collective/allreduce.py:56)."""
        return _CollectiveGroup(inputs, op).nodes


class _Collective:
    allreduce = _AllReduce()


collective = _Collective()


# --- interpreted execution ------------------------------------------------


def _exec_interpreted(node: DAGNode, args: tuple, kwargs: dict,
                      cache: Dict[int, Any]):
    key = id(node)
    if key in cache:
        return cache[key]
    if isinstance(node, InputNode):
        if kwargs or len(args) != 1:
            result = {"*args": args, **kwargs} if kwargs else args
        else:
            result = args[0]
    elif isinstance(node, InputAttributeNode):
        base = _exec_interpreted(node.input_node, args, kwargs, cache)
        if isinstance(node.key, str) and isinstance(base, dict):
            result = base[node.key]
        elif isinstance(base, dict) and "*args" in base:
            result = base["*args"][node.key]
        else:
            result = base[node.key]
    elif isinstance(node, AttributeNode):
        from .. import get

        base = _exec_interpreted(node.upstream, args, kwargs, cache)
        from .._private.object_ref import ObjectRef

        if isinstance(base, ObjectRef):
            base = get(base)
        result = base[node.key]
    elif isinstance(node, ClassMethodNode):
        from ..actor import ActorMethod

        call_args = [_exec_interpreted(a, args, kwargs, cache)
                     if isinstance(a, DAGNode) else a for a in node.args]
        call_kwargs = {k: _exec_interpreted(v, args, kwargs, cache)
                       if isinstance(v, DAGNode) else v
                       for k, v in node.kwargs.items()}
        method = ActorMethod(node.handle, node.method_name, node.options)
        result = method.remote(*call_args, **call_kwargs)
    elif isinstance(node, FunctionNode):
        call_args = [_exec_interpreted(a, args, kwargs, cache)
                     if isinstance(a, DAGNode) else a for a in node.args]
        call_kwargs = {k: _exec_interpreted(v, args, kwargs, cache)
                       if isinstance(v, DAGNode) else v
                       for k, v in node.kwargs.items()}
        result = node.remote_fn.remote(*call_args, **call_kwargs)
    elif isinstance(node, CollectiveNode):
        from .. import get, put

        vals = [_exec_interpreted(n, args, kwargs, cache)
                for n in node.group.inputs]
        resolved = get(list(vals))
        total = resolved[0]
        for v in resolved[1:]:
            total = total + v
        result = put(total)
    elif isinstance(node, MultiOutputNode):
        result = [_exec_interpreted(n, args, kwargs, cache)
                  for n in node.outputs]
    else:
        raise TypeError(f"cannot execute {type(node).__name__}")
    cache[key] = result
    return result
