"""Compiled DAG execution (ref: python/ray/dag/compiled_dag_node.py:806
CompiledDAG — allocate typed channels, start per-actor exec loops,
execute:2552).

Compilation turns submission-per-task into a standing dataflow machine:
every actor that owns DAG nodes runs ONE long-lived loop that reads its
input channels, runs its methods back-to-back, and writes its output
channels — zero scheduler involvement per execution. Channels are the
mutable shm buffers of ray_tpu.experimental.channel (the reference's
mutable plasma objects / N13).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ..experimental.channel import Channel, ChannelClosed, ChannelTimeout
from .nodes import (
    AttributeNode,
    ClassMethodNode,
    CollectiveNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)


def _dag_exec_loop(actor_self, spec_blob: bytes):
    """Runs ON the actor (injected via the dynamic-call method): the
    standing execution loop of this actor's DAG partition
    (ref: compiled_dag_node.py _execute_until / do_exec_tasks)."""
    spec = cloudpickle.loads(spec_blob)
    device_paths = set(spec.get("device_paths", ()))

    def _open(path: str):
        if path in device_paths:
            from ..experimental.device_channel import DeviceChannel

            return DeviceChannel(path)
        return Channel(path)

    readers: Dict[str, Channel] = {}
    writers: Dict[str, Channel] = {}
    for path in spec["read_paths"]:
        readers[path] = _open(path)
    for path in spec["write_paths"]:
        writers[path] = _open(path)

    def shutdown():
        for ch in writers.values():
            try:
                ch.close_write()
            except (ChannelTimeout, RuntimeError, ValueError, OSError):
                pass  # peer already gone / mapping torn down

    while True:
        results: Dict[int, Any] = {}
        chan_cache: Dict[str, Any] = {}

        def fetch(path: str, slot: int):
            if path not in chan_cache:
                chan_cache[path] = readers[path].read(slot)
            return chan_cache[path]

        def resolve(argspec):
            kind = argspec[0]
            if kind == "const":
                return argspec[1]
            if kind == "local":
                return results[argspec[1]]
            if kind == "local_attr":
                return _apply_keys(results[argspec[1]], argspec[2])
            if kind == "chan":
                _, path, slot, keys = argspec
                value = fetch(path, slot)
                if isinstance(value, _WrappedError):
                    # an upstream actor failed: forward the error
                    raise _Propagated(value)
                return _apply_keys(value, keys)
            raise ValueError(argspec)

        try:
            for step in spec["steps"]:
                if step["kind"] == "call":
                    args = [resolve(a) for a in step["args"]]
                    kwargs = {k: resolve(v)
                              for k, v in step["kwargs"].items()}
                    value = getattr(actor_self, step["method"])(
                        *args, **kwargs)
                elif step["kind"] == "collective_root":
                    value = results[step["src"]]
                    for path in step["contrib_paths"]:
                        value = value + fetch(path, 0)
                    if step["bcast_path"]:
                        writers[step["bcast_path"]].write(value)
                elif step["kind"] == "collective_leaf":
                    writers[step["contrib_path"]].write(
                        results[step["src"]])
                    value = fetch(step["bcast_path"], step["bcast_slot"])
                else:
                    raise ValueError(step["kind"])
                results[step["node_id"]] = value
                if step.get("out_path"):
                    writers[step["out_path"]].write(value)
        except ChannelClosed:
            shutdown()
            return True
        except BaseException as e:
            # surface through EVERY out channel, so the error travels the
            # dataflow graph hop by hop until the driver's result read
            # raises it (mid-chain failures included)
            err = e.err if isinstance(e, _Propagated) else \
                _WrappedError(repr(e))
            for path in spec["write_paths"]:
                try:
                    writers[path].write(err, timeout=5.0)
                except (ChannelTimeout, RuntimeError, TypeError,
                        ValueError, OSError):
                    pass  # dead consumer: it can't observe the error
            shutdown()
            if isinstance(e, _Propagated):
                return False  # upstream already raised the original
            raise


def _apply_keys(value, keys):
    """Apply a chain of index keys (node["a"]["b"] nests) to a node
    result / DAG input. A mixed positional+keyword input rides the
    channel as {"*args": args, **kwargs} (mirroring interpreted
    execution), so integer keys index the tuple inside."""
    for key in keys or ():
        if (isinstance(key, int) and isinstance(value, dict)
                and "*args" in value):
            value = value["*args"][key]
        else:
            value = value[key]
    return value


class _WrappedError:
    def __init__(self, msg: str):
        self.msg = msg


class _Propagated(Exception):
    """Wrapper for an upstream _WrappedError read off a channel."""

    def __init__(self, err: _WrappedError):
        super().__init__(err.msg)
        self.err = err


class CompiledDAGRef:
    """Result handle of one compiled execution
    (ref: compiled_dag_ref.py). ``get`` reads the DAG's output
    channel(s); results arrive in execution order."""

    def __init__(self, dag: "CompiledDAG", index: int):
        self._dag = dag
        self._index = index
        self._value = None
        self._fetched = False

    def get(self, timeout: Optional[float] = 60.0):
        if not self._fetched:
            self._dag._fetch_until(self._index, timeout)
        return self._value

    def __repr__(self):
        return f"CompiledDAGRef(exec={self._index})"


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 1 << 20,
                 max_inflight: int = 2):
        self.buffer_size = buffer_size_bytes
        self.max_inflight = max_inflight
        self._lock = threading.Lock()
        self._exec_count = 0
        self._next_fetch = 0
        self._row_vals: List[Any] = []
        self._pending: Dict[int, CompiledDAGRef] = {}
        self._torn_down = False
        # defaults BEFORE _build so a mid-build validation error leaves
        # teardown()-able state (channels allocate in topo order — the
        # ones created before the raise must not leak their shm files)
        self._channels: List[Channel] = []
        self._device_paths: set = set()
        self._input_channel = None
        self._outputs: List[Tuple[Channel, int, Any]] = []
        self._loop_refs: List[Any] = []
        try:
            self._build(root)
        except BaseException:
            for ch in self._channels:
                try:
                    ch.close()
                except (RuntimeError, ValueError, OSError):
                    pass
                try:  # unlink even when close() raised — the shm file
                    ch.unlink()  # is what must not leak
                except OSError:
                    pass
            self._torn_down = True
            raise

    # --- compilation ---

    def _build(self, root: DAGNode) -> None:
        outputs = (root.outputs if isinstance(root, MultiOutputNode)
                   else [root])
        self._multi = isinstance(root, MultiOutputNode)

        # topological node list (post-order DFS)
        order: List[DAGNode] = []
        seen: Dict[int, int] = {}

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = 1
            for dep in _deps(node):
                visit(dep)
            order.append(node)

        for out in outputs:
            visit(out)

        self._input_node = next(
            (n for n in order if isinstance(n, InputNode)), None)
        if self._input_node is None:
            # without an input channel the actor loops would free-run,
            # producing results decoupled from execute() calls
            raise ValueError(
                "experimental_compile requires the DAG to read from an "
                "InputNode (build it under `with InputNode() as inp:`)")
        node_ids = {id(n): i for i, n in enumerate(order)}
        actor_of: Dict[int, Any] = {}
        for n in order:
            if isinstance(n, ClassMethodNode):
                actor_of[node_ids[id(n)]] = n.handle
            elif isinstance(n, CollectiveNode):
                actor_of[node_ids[id(n)]] = n.group.inputs[n.index].handle

        def actor_key(handle):
            return handle.actor_id.hex()

        # consumers per producer node: which OTHER actors read it + driver
        remote_consumers: Dict[int, List[str]] = {}
        driver_reads: Dict[int, bool] = {}

        def unwrap(node: DAGNode):
            """Peel (possibly nested) attribute access down to the real
            producer; returns (producer, key_chain)."""
            keys: List[Any] = []
            while True:
                if isinstance(node, InputAttributeNode):
                    keys.append(node.key)
                    node = node.input_node
                elif isinstance(node, AttributeNode):
                    keys.append(node.key)
                    node = node.upstream
                else:
                    return node, tuple(reversed(keys))

        def note_consumer(producer: DAGNode, consumer_actor: Optional[str]):
            producer, _ = unwrap(producer)
            pid = node_ids[id(producer)]
            p_actor = (None if isinstance(producer, InputNode)
                       else actor_key(actor_of[pid]))
            if consumer_actor is not None and consumer_actor == p_actor:
                return  # same actor: local variable, no channel
            if consumer_actor is None:
                driver_reads[pid] = True
            else:
                remote_consumers.setdefault(pid, [])
                if consumer_actor not in remote_consumers[pid]:
                    remote_consumers[pid].append(consumer_actor)

        for n in order:
            if isinstance(n, ClassMethodNode):
                me = actor_key(n.handle)
                for a in list(n.args) + list(n.kwargs.values()):
                    if isinstance(a, DAGNode):
                        note_consumer(a, me)
        for out in outputs:
            note_consumer(out, None)

        # channels: one per produced value that crosses a process boundary
        chan_of: Dict[int, Channel] = {}
        slot_of: Dict[Tuple[int, str], int] = {}
        for n in order:
            pid = node_ids[id(n)]
            consumers = remote_consumers.get(pid, [])
            n_readers = len(consumers) + (1 if driver_reads.get(pid) else 0)
            if n_readers == 0:
                continue
            if not isinstance(n, (InputNode, ClassMethodNode,
                                  CollectiveNode)):
                continue
            if getattr(n, "device_transport", False):
                # with_device_transport(): this edge's jax arrays move
                # peer-to-peer over the PJRT transfer fabric
                if driver_reads.get(pid) or len(consumers) != 1:
                    raise ValueError(
                        "with_device_transport() edges need exactly one "
                        "remote consumer and no driver read (DeviceChannel "
                        "is 1:1; route driver-bound values over the "
                        "default shm lane)")
                from ..experimental.device_channel import DeviceChannel

                ch = DeviceChannel(capacity=self.buffer_size)
                self._device_paths.add(ch.path)
            else:
                ch = Channel(num_readers=n_readers,
                             capacity=self.buffer_size)
            self._channels.append(ch)
            chan_of[pid] = ch
            for slot, actor in enumerate(consumers):
                slot_of[(pid, actor)] = slot
            if driver_reads.get(pid):
                slot_of[(pid, "__driver__")] = len(consumers)

        # collective plumbing
        coll_channels: Dict[int, Dict[str, Any]] = {}
        groups = {}
        for n in order:
            if isinstance(n, CollectiveNode) and id(n.group) not in groups:
                groups[id(n.group)] = n.group
        for group in groups.values():
            handles = [inp.handle for inp in group.inputs]
            contribs = [Channel(num_readers=1, capacity=self.buffer_size)
                        for _ in handles[1:]]
            # single-participant allreduce is the identity: no broadcast
            # channel (a reader-less channel would block on execution 2)
            bcast = (Channel(num_readers=len(handles) - 1,
                             capacity=self.buffer_size)
                     if len(handles) > 1 else None)
            self._channels.extend(contribs + ([bcast] if bcast else []))
            coll_channels[id(group)] = {
                "contribs": contribs, "bcast": bcast}

        # per-actor step specs
        specs: Dict[str, Dict[str, Any]] = {}

        def spec_for(handle) -> Dict[str, Any]:
            key = actor_key(handle)
            if key not in specs:
                specs[key] = {"handle": handle, "steps": [],
                              "read_paths": set(), "write_paths": set()}
            return specs[key]

        def argspec(a, me: str):
            if not isinstance(a, DAGNode):
                return ("const", a)
            producer, keys = unwrap(a)
            pid = node_ids[id(producer)]
            p_actor = (None if isinstance(producer, InputNode)
                       else actor_key(actor_of[pid]))
            if p_actor == me:
                if not keys:
                    return ("local", pid)
                return ("local_attr", pid, keys)
            ch = chan_of[pid]
            slot = slot_of[(pid, me)]
            return ("chan", ch.path, slot, keys)

        for n in order:
            pid = node_ids[id(n)]
            if isinstance(n, ClassMethodNode):
                me = actor_key(n.handle)
                spec = spec_for(n.handle)
                out_ch = chan_of.get(pid)
                step = {
                    "kind": "call", "node_id": pid,
                    "method": n.method_name,
                    "args": [argspec(a, me) for a in n.args],
                    "kwargs": {k: argspec(v, me)
                               for k, v in n.kwargs.items()},
                    "out_path": out_ch.path if out_ch else None,
                }
                spec["steps"].append(step)
            elif isinstance(n, CollectiveNode):
                group = n.group
                plumb = coll_channels[id(group)]
                handle = group.inputs[n.index].handle
                me = actor_key(handle)
                spec = spec_for(handle)
                src = node_ids[id(group.inputs[n.index])]
                out_ch = chan_of.get(pid)
                if n.index == 0:
                    step = {
                        "kind": "collective_root", "node_id": pid,
                        "src": src,
                        "contrib_paths": [c.path
                                          for c in plumb["contribs"]],
                        "bcast_path": (plumb["bcast"].path
                                       if plumb["bcast"] else None),
                        "out_path": out_ch.path if out_ch else None,
                    }
                else:
                    step = {
                        "kind": "collective_leaf", "node_id": pid,
                        "src": src,
                        "contrib_path": plumb["contribs"][n.index - 1].path,
                        "bcast_path": plumb["bcast"].path,
                        "bcast_slot": n.index - 1,
                        "out_path": out_ch.path if out_ch else None,
                    }
                spec["steps"].append(step)

        # read/write path sets per spec
        for spec in specs.values():
            for step in spec["steps"]:
                if step.get("out_path"):
                    spec["write_paths"].add(step["out_path"])
                if step["kind"] == "call":
                    for a in (list(step["args"])
                              + list(step["kwargs"].values())):
                        if a[0] == "chan":
                            spec["read_paths"].add(a[1])
                elif step["kind"] == "collective_root":
                    spec["read_paths"].update(step["contrib_paths"])
                    if step["bcast_path"]:
                        spec["write_paths"].add(step["bcast_path"])
                elif step["kind"] == "collective_leaf":
                    spec["write_paths"].add(step["contrib_path"])
                    spec["read_paths"].add(step["bcast_path"])

        # driver-side output bindings
        self._outputs: List[Tuple[Channel, int, Any]] = []
        for out in outputs:
            producer, keys = unwrap(out)
            pid = node_ids[id(producer)]
            ch = chan_of[pid]
            self._outputs.append((ch, slot_of[(pid, "__driver__")], keys))

        # driver-side input binding
        self._input_channel = None
        if self._input_node is not None:
            ipid = node_ids[id(self._input_node)]
            self._input_channel = chan_of.get(ipid)
            for spec in specs.values():
                for step in spec["steps"]:
                    if step["kind"] != "call":
                        continue
                    for a in (list(step["args"])
                              + list(step["kwargs"].values())):
                        if (a[0] == "chan" and self._input_channel
                                and a[1] == self._input_channel.path):
                            spec["read_paths"].add(a[1])

        # launch the loops (fire-and-forget)
        from ..actor import ActorMethod

        self._loop_refs = []
        loop_blob = cloudpickle.dumps(_dag_exec_loop)
        for spec in specs.values():
            handle = spec.pop("handle")
            payload = dict(spec)
            payload["read_paths"] = sorted(payload["read_paths"])
            payload["write_paths"] = sorted(payload["write_paths"])
            payload["device_paths"] = sorted(self._device_paths)
            method = ActorMethod(handle, "_rtpu_dyn_call")
            self._loop_refs.append(
                method.remote(loop_blob, cloudpickle.dumps(payload)))

    # --- execution ---

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        with self._lock:
            if self._torn_down:
                raise RuntimeError("CompiledDAG was torn down")
            if (self._exec_count - self._next_fetch) >= self.max_inflight:
                raise RuntimeError(
                    f"too many in-flight executions "
                    f"(max_inflight={self.max_inflight}); get() pending "
                    f"results first")
            if self._input_channel is not None:
                if kwargs or len(args) != 1:
                    value = ({"*args": args, **kwargs} if kwargs
                             else args)
                else:
                    value = args[0]
                self._input_channel.write(value, timeout=60.0)
            ref = CompiledDAGRef(self, self._exec_count)
            self._pending[self._exec_count] = ref
            self._exec_count += 1
            return ref

    def _fetch_until(self, index: int, timeout: Optional[float]) -> None:
        with self._lock:
            while self._next_fetch <= index:
                # resume a partially-read output row (a ChannelTimeout
                # mid-row must not desync channels whose cursor already
                # advanced), hence the persistent _row_vals cursor
                while len(self._row_vals) < len(self._outputs):
                    ch, slot, keys = self._outputs[len(self._row_vals)]
                    try:
                        v = ch.read(slot, timeout=timeout)
                    except ChannelClosed:
                        self.teardown()
                        raise RuntimeError(
                            "compiled DAG channels closed unexpectedly "
                            "(an actor loop exited)") from None
                    if isinstance(v, _WrappedError):
                        self.teardown()
                        raise RuntimeError(
                            f"compiled DAG task failed: {v.msg}")
                    self._row_vals.append(_apply_keys(v, keys))
                vals, self._row_vals = self._row_vals, []
                ref = self._pending.pop(self._next_fetch)
                ref._value = vals if self._multi else vals[0]
                ref._fetched = True
                self._next_fetch += 1

    # --- lifecycle ---

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        if self._input_channel is not None:
            try:
                self._input_channel.close_write()
            except (ChannelTimeout, RuntimeError, ValueError, OSError):
                pass  # loops already gone; draining below still runs
        # drain leftover outputs so mid-pipeline writers unblock
        for ch, slot, _ in self._outputs:
            for _ in range(self.max_inflight + 1):
                try:
                    ch.read(slot, timeout=0.2)
                except (ChannelClosed, ChannelTimeout):
                    break
                except (RuntimeError, ValueError, OSError,
                        EOFError, AttributeError):
                    break  # torn-down mapping or a half-written payload
        for ch in self._channels:
            ch.close()
            ch.unlink()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _deps(node: DAGNode) -> List[DAGNode]:
    if isinstance(node, ClassMethodNode):
        return [a for a in list(node.args) + list(node.kwargs.values())
                if isinstance(a, DAGNode)]
    if isinstance(node, (InputAttributeNode,)):
        return [node.input_node]
    if isinstance(node, AttributeNode):
        return [node.upstream]
    if isinstance(node, CollectiveNode):
        return list(node.group.inputs)
    if isinstance(node, MultiOutputNode):
        return list(node.outputs)
    return []
