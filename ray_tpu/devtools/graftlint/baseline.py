"""Baseline (suppression) file: the ratchet that lets graftlint gate CI.

The baseline records the fingerprints of known, triaged findings.
``--baseline FILE`` makes the run exit non-zero only on findings *not*
in the file — new hazards gate, old ones don't block unrelated PRs.
``--update-baseline`` rewrites the file from the current findings
(after fixing something, or after deliberately accepting a new one).

Fixed findings show up as *stale* baseline entries; they are reported
(so the file gets pruned) but never fail the run.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .findings import Finding

BASELINE_VERSION = 1


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> recorded entry. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: unsupported baseline version {data.get('version')}")
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save(path: str, findings: List[Finding]) -> None:
    entries = [{
        "fingerprint": f.fingerprint,
        "pass": f.pass_name,
        "rule": f.rule,
        "path": f.path,
        "scope": f.scope,
        "message": f.message,
    } for f in sorted(findings,
                      key=lambda f: (f.path, f.pass_name, f.scope,
                                     f.fingerprint))]
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION, "findings": entries},
                  f, indent=1, ensure_ascii=False)
        f.write("\n")


def diff(findings: List[Finding],
         baseline: Dict[str, dict]) -> Tuple[List[Finding], List[dict]]:
    """(new findings not in baseline, stale baseline entries)."""
    current = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    stale = [e for fp, e in sorted(baseline.items())
             if fp not in current]
    return new, stale
