"""Pass 5 — wire-protocol consistency.

The wire layer (_private/wire.py) is a hand-maintained set of tag
registries — ids, structs, exceptions, msgpack EXT codes. Nothing
type-checks them: a duplicate tag silently shadows the earlier class
(decode returns the wrong type cluster-wide), a class registered twice
encodes ambiguously, and a tag special-cased in the encoder but not the
decoder (or vice versa) is a ghost that round-trips to a WireError in
production only.

Applies to any module that calls ``register_id`` / ``register_struct``
/ ``register_exception`` (so fixtures can pin behavior), and checks:

  * ``duplicate-tag``       — one tag registered twice in a registry
  * ``duplicate-class``     — one class under two tags in a registry
  * ``duplicate-ext-code``  — two ``EXT_*`` constants share a value
  * ``ghost-tag``           — a literal tag special-cased in the encode
    path (``_default``) or decode path (``_ext_hook``) but not
    registered AND not handled on the other side
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._astutil import iter_functions, terminal_attr
from .findings import Finding

PASS_NAME = "wire"

_REGISTRARS = {"register_id": "id", "register_struct": "struct",
               "register_exception": "exception"}


def _int_const(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _literal_ints(fnode) -> Set[int]:
    """Integer literals used in comparisons or as list heads inside a
    function — the special-case tag shapes (`tag == 100`,
    `_pack([100, ...])`)."""
    out: Set[int] = set()
    for node in ast.walk(fnode):
        if isinstance(node, ast.Compare):
            for cmp in [node.left] + list(node.comparators):
                v = _int_const(cmp)
                if v is not None:
                    out.add(v)
        elif isinstance(node, ast.List) and node.elts:
            v = _int_const(node.elts[0])
            if v is not None:
                out.add(v)
    return out


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    registrations: Dict[str, List[Tuple[int, str, int]]] = {}  # kind -> [(tag, cls, line)]
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_attr(node.func)
        kind = _REGISTRARS.get(name or "")
        if kind is None or len(node.args) < 2:
            continue
        tag = _int_const(node.args[0])
        if tag is None:
            continue
        cls = terminal_attr(node.args[1]) or "<expr>"
        registrations.setdefault(kind, []).append((tag, cls, node.lineno))

    if not registrations:
        return []
    findings: List[Finding] = []

    for kind, entries in registrations.items():
        by_tag: Dict[int, List[Tuple[str, int]]] = {}
        by_cls: Dict[str, List[Tuple[int, int]]] = {}
        for tag, cls, line in entries:
            by_tag.setdefault(tag, []).append((cls, line))
            by_cls.setdefault(cls, []).append((tag, line))
        for tag, uses in sorted(by_tag.items()):
            if len(uses) > 1:
                names = ", ".join(f"{c} (line {ln})" for c, ln in uses)
                findings.append(Finding(
                    PASS_NAME, "duplicate-tag", path, uses[-1][1],
                    "<module>",
                    f"{kind} tag {tag} registered {len(uses)}x: {names} —"
                    " later registration silently shadows the earlier",
                    detail=f"{kind} tag {tag}"))
        for cls, uses in sorted(by_cls.items()):
            if len(uses) > 1:
                tags = ", ".join(str(t) for t, _ in uses)
                findings.append(Finding(
                    PASS_NAME, "duplicate-class", path, uses[-1][1],
                    "<module>",
                    f"{kind} class {cls} registered under tags {tags} —"
                    " encode is ambiguous",
                    detail=f"{kind} class {cls}"))

    # EXT_* constant collisions
    ext: Dict[int, List[Tuple[str, int]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.startswith("EXT_"):
            v = _int_const(node.value)
            if v is not None:
                ext.setdefault(v, []).append(
                    (node.targets[0].id, node.lineno))
    for v, uses in sorted(ext.items()):
        if len(uses) > 1:
            names = ", ".join(n for n, _ in uses)
            findings.append(Finding(
                PASS_NAME, "duplicate-ext-code", path, uses[-1][1],
                "<module>",
                f"EXT codes {names} share value {v} — the ext_hook"
                " dispatch is ambiguous",
                detail=f"ext code {v}"))

    # ghost tags: literals special-cased in _default (encode) and
    # _ext_hook (decode) must be registered or handled on BOTH sides
    encode_lits: Set[int] = set()
    decode_lits: Set[int] = set()
    for qualname, fnode, _cls in iter_functions(tree):
        if fnode.name == "_default":
            encode_lits |= _literal_ints(fnode)
        elif fnode.name == "_ext_hook":
            decode_lits |= _literal_ints(fnode)
    registered: Set[int] = {t for entries in registrations.values()
                            for t, _, _ in entries}
    ext_values = set(ext.keys())
    for tag in sorted((encode_lits ^ decode_lits)
                      - registered - ext_values):
        side = "encode (_default)" if tag in encode_lits \
            else "decode (_ext_hook)"
        findings.append(Finding(
            PASS_NAME, "ghost-tag", path, 1, "<module>",
            f"tag {tag} is special-cased only on the {side} side and"
            " never registered — peers cannot round-trip it",
            detail=f"ghost tag {tag}"))
    return findings
