"""Pass 8 — rpc-timeout pass: control-plane waits with no bound.

A lost frame on an unbounded await is the purest form of the
fault-becomes-hang failure mode: nothing raises, nothing logs, the
caller just never resumes, and the stall sentinel inherits the
debugging job. Two rules:

  * ``unbounded-rpc-await`` — ``await x.call(...)`` with no
    ``timeout=`` kwarg. In this codebase ``.call`` is the RPC verb
    (RpcClient.call / GcsClient-style wrappers take ``timeout=``);
    ``call_retrying`` is exempt (it carries a per-try timeout
    default), as is a ``.call`` wrapped in ``asyncio.wait_for`` —
    there the awaited expression is the ``wait_for``, not the
    ``.call``, so the pattern is naturally blessed.
  * ``uncapped-retry`` — a ``while True`` retry loop (it contains a
    ``break``/``return`` success exit AND a try/except that does not
    re-raise) sleeping a *constant* interval: no backoff cap, no
    deadline, so a persistent fault spins forever at fixed frequency.
    Periodic daemon loops (no loop exit) and loops whose handler
    re-raises past a deadline are exempt, as are sleeps with computed
    (escalating) arguments.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from ._astutil import ImportMap, iter_functions
from .findings import Finding

PASS_NAME = "rpc-timeout"

_SLEEPS = {"time.sleep", "asyncio.sleep"}


def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def _owner_map(tree: ast.Module) -> Dict[int, str]:
    owner: Dict[int, str] = {}
    for qualname, fnode, _cls in iter_functions(tree):
        for sub in ast.walk(fnode):
            owner[id(sub)] = qualname
    return owner


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    imports = ImportMap(tree)
    owner = _owner_map(tree)

    def scope_of(node: ast.AST) -> str:
        return owner.get(id(node), "<module>")

    for node in ast.walk(tree):
        # --- unbounded-rpc-await ---
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "call" \
                    and not any(kw.arg == "timeout" for kw in call.keywords):
                method = ""
                if call.args and isinstance(call.args[0], ast.Constant) \
                        and isinstance(call.args[0].value, str):
                    method = call.args[0].value
                findings.append(Finding(
                    PASS_NAME, "unbounded-rpc-await", path, node.lineno,
                    scope_of(node),
                    f"`await ....call({method or '...'!r}...)` has no "
                    "timeout= bound — a lost frame hangs the caller "
                    "instead of raising",
                    detail=f"unbounded call {method or '<dynamic>'}"))

        # --- uncapped-retry ---
        if isinstance(node, ast.While) \
                and isinstance(node.test, ast.Constant) \
                and node.test.value is True:
            has_exit = False
            has_try = False
            bounded_handler = False
            const_sleep = None
            for sub in _walk_skip_defs(node):
                if isinstance(sub, (ast.Break, ast.Return)):
                    has_exit = True
                elif isinstance(sub, ast.Try):
                    has_try = True
                    # a handler that can raise/return/break is a bound:
                    # the deadline-reraise and check-stop-flag idioms
                    for handler in sub.handlers:
                        if any(isinstance(n, (ast.Raise, ast.Return,
                                              ast.Break))
                               for n in ast.walk(handler)):
                            bounded_handler = True
                elif isinstance(sub, ast.Call):
                    if imports.resolve_call(sub) in _SLEEPS and sub.args \
                            and isinstance(sub.args[0], ast.Constant):
                        const_sleep = sub.args[0].value
            if has_exit and has_try and not bounded_handler \
                    and const_sleep is not None:
                findings.append(Finding(
                    PASS_NAME, "uncapped-retry", path, node.lineno,
                    scope_of(node),
                    "`while True` retry loop with a constant "
                    f"sleep({const_sleep}) and an except that never "
                    "re-raises — no backoff cap or deadline, a "
                    "persistent fault retries forever",
                    detail=f"uncapped retry sleep={const_sleep}"))
    return findings
