"""Pass 6 — swallow pass: exception flow that silently dies.

The error plane's analog of the blocking pass: a node fault that lands
in a discard-shaped handler becomes the hang the stall sentinel later
has to attribute, instead of an error the caller could act on *now*.
Three rules:

  * ``absorbs-cancellation`` (hard class — the baseline must stay empty
    of these): a clause that can catch ``asyncio.CancelledError``,
    ``KeyboardInterrupt``, or ``CollectiveTimeoutError`` — bare
    ``except:``, ``except BaseException``, or naming one of them
    explicitly — whose body neither re-raises nor forwards the bound
    exception. Absorbing cancellation on the io loop turns task
    cancellation (cancel-the-loser hedging, loop drain at shutdown)
    into a task that keeps running.
  * ``silent-swallow`` — a broad clause (``Exception``/``BaseException``
    /bare) whose body *discards* the exception: only ``pass``/
    ``continue``/constant ``return``/log-calls, no re-raise, no use of
    the bound variable. Best-effort cleanup sites get ratcheted into
    the baseline; new ones gate.
  * ``raise-without-from`` — ``raise X(...)`` inside an ``except``
    without ``from``: the wrapped error loses its explicit cause chain,
    so fault attribution stops at the wrapper.

False-positive guards (fixture-pinned): a clause whose body contains
any ``raise``; a handler that *uses* the bound exception outside
logging (error forwarded over the wire, stored, wrapped with ``from``);
an earlier clause in the same ``try`` that catches the cancellation
type and re-raises; handlers inside ``__del__`` (a finalizer must never
raise — swallowing there is the contract, and the finalizer pass owns
that scope); non-broad clauses with fallback logic; handlers that
capture the traceback (``format_exc``/``exc_info``) for later
surfacing; fork/process boundaries whose try calls ``os._exit``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ._astutil import dotted, iter_functions, terminal_attr
from .findings import Finding

PASS_NAME = "swallow"

# types whose absorption turns faults into hangs (cancellation never
# reaches the loop's drain; a collective timeout never reaches the
# caller that would re-form the gang)
_CANCELLATION_TYPES = {"CancelledError", "KeyboardInterrupt",
                       "CollectiveTimeoutError"}
_BROAD_TYPES = {"Exception", "BaseException"}

_LOGGISH = {"print", "debug", "info", "warning", "warn", "error",
            "exception", "critical", "log", "write"}


def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    (their bodies are separate scopes, analyzed on their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def _clause_types(handler: ast.ExceptHandler) -> Optional[List[str]]:
    """Terminal names of the caught types; None = bare ``except:``."""
    t = handler.type
    if t is None:
        return None
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    return [terminal_attr(n) or "<expr>" for n in nodes]


def _clause_repr(types: Optional[List[str]]) -> str:
    if types is None:
        return "except:"
    return f"except {', '.join(types)}"


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise)
               for n in _walk_skip_defs_body(handler))


def _walk_skip_defs_body(handler: ast.ExceptHandler) -> Iterable[ast.AST]:
    for stmt in handler.body:
        yield stmt
        yield from _walk_skip_defs(stmt)


def _is_loggish_call(call: ast.Call) -> bool:
    name = terminal_attr(call.func)
    return name in _LOGGISH


def _uses_exc_var(handler: ast.ExceptHandler) -> bool:
    """The bound name is referenced outside log-ish calls: the error is
    forwarded/stored/wrapped — handled, not discarded."""
    if handler.name is None:
        return False
    log_spans: List[ast.Call] = []
    for n in _walk_skip_defs_body(handler):
        if isinstance(n, ast.Call) and _is_loggish_call(n):
            log_spans.append(n)
    in_logs = {id(sub) for call in log_spans for sub in ast.walk(call)}
    for n in _walk_skip_defs_body(handler):
        if isinstance(n, ast.Name) and n.id == handler.name \
                and id(n) not in in_logs:
            return True
    return False


def _captures_exc_info(handler: ast.ExceptHandler) -> bool:
    """The handler stores the live traceback (``format_exc``/
    ``exc_info``) — the thread-boundary error-trap idiom where the
    fault is surfaced later via poll()/status, not discarded."""
    for n in _walk_skip_defs_body(handler):
        if isinstance(n, ast.Call) \
                and terminal_attr(n.func) in ("format_exc", "exc_info"):
            return True
    return False


def _exits_process(try_node: ast.Try, handler: ast.ExceptHandler) -> bool:
    """The handler (or the try's finally) calls ``os._exit``: a fork/
    process boundary that must never unwind — catching everything is
    the contract there, not a hazard."""
    nodes = list(_walk_skip_defs_body(handler))
    for stmt in try_node.finalbody:
        nodes.append(stmt)
        nodes.extend(_walk_skip_defs(stmt))
    return any(isinstance(n, ast.Call)
               and terminal_attr(n.func) == "_exit" for n in nodes)


def _discard_shaped(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/continue/break/constant-return/log calls: the
    exception evaporates. Any assignment or non-log call counts as
    fallback logic (handling), not discarding."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None or isinstance(stmt.value, ast.Constant):
                continue
            return False
        if isinstance(stmt, ast.Expr):
            if isinstance(stmt.value, ast.Constant):
                continue  # docstring / ellipsis
            if isinstance(stmt.value, ast.Call) \
                    and _is_loggish_call(stmt.value):
                continue
            return False
        return False
    return True


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []

    # innermost enclosing function per Try/Raise, for scope + __del__
    owner_of: Dict[int, str] = {}
    fname_of: Dict[str, str] = {}
    for qualname, fnode, _cls in iter_functions(tree):
        fname_of[qualname] = fnode.name
        for sub in ast.walk(fnode):
            owner_of[id(sub)] = qualname  # inner defs overwrite

    def scope_of(node: ast.AST) -> str:
        return owner_of.get(id(node), "<module>")

    def in_finalizer(node: ast.AST) -> bool:
        return fname_of.get(scope_of(node), "") == "__del__"

    def emit(rule: str, node: ast.AST, message: str, detail: str):
        findings.append(Finding(PASS_NAME, rule, path, node.lineno,
                                scope_of(node), message, detail=detail))

    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            # cancellation types already caught-and-reraised by an
            # earlier clause bless later broad clauses for those types
            reraised_earlier: Set[str] = set()
            for handler in node.handlers:
                types = _clause_types(handler)
                crepr = _clause_repr(types)
                reraise = _reraises(handler)
                uses_var = (_uses_exc_var(handler)
                            or _captures_exc_info(handler)
                            or _exits_process(node, handler))
                if types is None or "BaseException" in types:
                    absorbed = set(_CANCELLATION_TYPES)
                else:
                    absorbed = set(types) & _CANCELLATION_TYPES
                absorbed -= reraised_earlier
                if reraise:
                    reraised_earlier |= (set(_CANCELLATION_TYPES)
                                         if types is None
                                         or "BaseException" in types
                                         else absorbed)
                if absorbed and not reraise and not uses_var \
                        and not in_finalizer(handler):
                    emit("absorbs-cancellation", handler,
                         f"`{crepr}` can absorb "
                         f"{'/'.join(sorted(absorbed))} without re-raising"
                         " — cancellation/interrupt dies here and the"
                         " task runs on (hang, not error)",
                         detail=f"absorbs {crepr}")
                    continue  # one finding per clause
                broad = types is None or bool(set(types) & _BROAD_TYPES)
                if broad and not reraise and not uses_var \
                        and _discard_shaped(handler) \
                        and not in_finalizer(handler):
                    emit("silent-swallow", handler,
                         f"`{crepr}` discards the exception (pass/"
                         "log-only, no re-raise) — the fault surfaces"
                         " nowhere",
                         detail=f"swallow {crepr}")

            # raise X(...) without `from` inside a handler
            for handler in node.handlers:
                for sub in _walk_skip_defs_body(handler):
                    if isinstance(sub, ast.Try):
                        break  # nested try owns its own handlers' raises
                    if not isinstance(sub, ast.Raise):
                        continue
                    if sub.exc is None or sub.cause is not None:
                        continue  # bare re-raise / explicit chain
                    if not isinstance(sub.exc, ast.Call):
                        continue  # `raise e` re-raise of the bound error
                    name = dotted(sub.exc.func) or "<exc>"
                    emit("raise-without-from", sub,
                         f"`raise {name}(...)` inside `except` without"
                         " `from` — the cause chain is implicit and"
                         " attribution stops at the wrapper",
                         detail=f"raise {name} no-cause")
    return findings
