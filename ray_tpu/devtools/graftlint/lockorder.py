"""Pass 2 — static lock-order analyzer.

Builds a per-module lock-acquisition graph and reports cycles as
potential deadlocks (rule ``lock-cycle``).

Nodes are lock *classes* in the lockdep sense — canonical names like
``Store._lock`` (``self._x`` inside class ``Store``) or a module-level
lock's own name — not instances: an AB/BA inversion between two methods
is a hazard even if each run only ever touches one instance.

Edges:
  * **lexical**: ``with a:`` containing ``with b:`` adds a→b;
  * **call-through**: a ``self.m()`` call made while holding ``a`` adds
    a→x for every lock ``x`` that same-class method ``m`` (transitively,
    same class only) acquires.

Guards: re-acquiring the same canonical lock never adds a self-edge
(RLock re-entrancy is the witness's problem, not an ordering one), and
``async with`` asyncio locks participate like thread locks — two tasks
on one loop invert the same way two threads do.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._astutil import (ImportMap, collect_lock_names, dotted,
                       iter_functions, terminal_attr)
from .findings import Finding

PASS_NAME = "lock-order"


def _canon(expr: ast.AST, cls_name: Optional[str],
           locks) -> Optional[str]:
    """Canonical lock-class key for a with-item expression, or None if
    it doesn't look like a lock."""
    if not locks.looks_like_lock(expr):
        return None
    name = dotted(expr)
    if name is None:
        return None
    if name.startswith("self."):
        owner = cls_name or "<func>"
        return f"{owner}.{name[5:]}"
    return name


class _Edge:
    __slots__ = ("src", "dst", "line", "scope", "via")

    def __init__(self, src, dst, line, scope, via):
        self.src, self.dst = src, dst
        self.line, self.scope, self.via = line, scope, via


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    imports = ImportMap(tree)
    locks = collect_lock_names(tree, imports)

    # per-function: locks acquired anywhere inside (for call-through),
    # and raw edges from lexical nesting / held-set call sites
    edges: List[_Edge] = []
    func_acquires: Dict[Tuple[Optional[str], str], Set[str]] = {}
    calls_under_lock: List[Tuple[Set[str], Optional[str], str, int, str]] = []
    intra_calls: Dict[Tuple[Optional[str], str], Set[str]] = {}

    for qualname, fnode, cls in iter_functions(tree):
        cls_name = cls.name if cls is not None else None
        key = (cls_name, fnode.name)
        acquired: Set[str] = set()
        callees: Set[str] = set()

        def walk(node, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # analyzed as its own function
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    got: List[str] = []
                    for item in child.items:
                        lk = _canon(item.context_expr, cls_name, locks)
                        if lk is None:
                            continue
                        acquired.add(lk)
                        for h in held + tuple(got):
                            if h != lk:
                                edges.append(_Edge(
                                    h, lk, child.lineno, qualname,
                                    "nested-with"))
                        got.append(lk)
                    new_held = held + tuple(
                        g for g in got if g not in held)
                elif isinstance(child, ast.Call):
                    fn = child.func
                    if (isinstance(fn, ast.Attribute)
                            and isinstance(fn.value, ast.Name)
                            and fn.value.id == "self"):
                        callees.add(fn.attr)
                        if held:
                            calls_under_lock.append(
                                (set(held), cls_name, fn.attr,
                                 child.lineno, qualname))
                walk(child, new_held)

        walk(fnode, ())
        func_acquires.setdefault(key, set()).update(acquired)
        intra_calls.setdefault(key, set()).update(callees)

    # transitive closure of same-class acquisitions: what does calling
    # self.m() eventually lock?
    closure: Dict[Tuple[Optional[str], str], Set[str]] = {
        k: set(v) for k, v in func_acquires.items()}
    changed = True
    while changed:
        changed = False
        for key, callees in intra_calls.items():
            cls_name = key[0]
            acc = closure.setdefault(key, set())
            for callee in callees:
                sub = closure.get((cls_name, callee))
                if sub and not sub <= acc:
                    acc |= sub
                    changed = True

    for held, cls_name, callee, line, scope in calls_under_lock:
        for lk in sorted(closure.get((cls_name, callee), ())):
            for h in held:
                if h != lk:
                    edges.append(_Edge(h, lk, line, scope,
                                       f"call self.{callee}()"))

    # ---- cycle detection over the dedup'd graph
    adj: Dict[str, Set[str]] = {}
    best_edge: Dict[Tuple[str, str], _Edge] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)
        best_edge.setdefault((e.src, e.dst), e)

    findings: List[Finding] = []
    reported: Set[frozenset] = set()

    def path_exists(src: str, dst: str) -> Optional[List[str]]:
        stack, seen, parent = [src], {src}, {}
        while stack:
            n = stack.pop()
            if n == dst:
                chain, cur = [dst], dst
                while cur != src:
                    cur = parent[cur]
                    chain.append(cur)
                return list(reversed(chain))
            for m in adj.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    parent[m] = n
                    stack.append(m)
        return None

    for (a, b), e in sorted(best_edge.items(),
                            key=lambda kv: kv[1].line):
        back = path_exists(b, a)
        if back is None:
            continue
        cycle = frozenset([a] + back)
        if cycle in reported:
            continue
        reported.add(cycle)
        legs = []
        chain = [a] + back
        for s, d in zip(chain, chain[1:]):
            le = best_edge.get((s, d))
            if le is not None:
                legs.append(f"{s}→{d} at {le.scope} "
                            f"(line {le.line}, {le.via})")
        findings.append(Finding(
            PASS_NAME, "lock-cycle", path, e.line, e.scope,
            "lock-order cycle (potential deadlock): " + "; ".join(legs),
            detail="cycle:" + "→".join(sorted(cycle))))
    return findings
