"""Pass 7 — cleanup pass: resource lifecycles without a release path.

The control plane holds kernel-backed resources everywhere: shm
segments, unix sockets, file handles, temp spill files, background rpc
tasks. A raise between acquire and release strands the resource until
GC gets around to the finalizer — on a raylet that can mean an fd or a
pinned shm segment held across the whole incident. Two rules:

  * ``unguarded-acquire`` — a local name bound from a resource
    constructor (``open``, ``socket.socket``, ``SharedMemory``,
    ``mmap.mmap``, ``os.open``, ``NamedTemporaryFile``...) that is
    neither ``with``-managed nor released in a ``finally``, while a
    raise-capable call sits between acquire and release. Split into
    two details: the name is released but only on the happy path
    (``release-not-in-finally``), or never released in this scope at
    all (``never-released``).
  * ``stop-leaks-resource`` — a class whose ``__init__``/``start``
    stores a resource or background task on ``self`` and which HAS a
    lifecycle method (``stop``/``shutdown``/``close``/...), but no
    lifecycle method ever touches that attribute: shutdown completes
    "cleanly" with the ring thread / server socket / retained task
    still live.

False-positive guards (fixture-pinned): ``with`` statements; release
inside any ``finally``; ownership escape — the name is returned,
yielded, stored onto an attribute/subscript, or appended into a
collection (the resource outlives the scope on purpose); acquire
functions whose result is immediately guarded by ``try/finally``;
classes with no lifecycle method at all (value objects — nothing to
wire the release into); attributes the lifecycle methods do reference,
even via delegation.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ._astutil import ImportMap, dotted, iter_functions, terminal_attr
from .findings import Finding

PASS_NAME = "cleanup"

# constructors (import-resolved) whose return value is a kernel-backed
# resource the caller must release
_ACQUIRERS = {
    "open", "os.open", "os.fdopen", "os.pipe",
    "socket.socket", "socket.create_connection", "socket.socketpair",
    "mmap.mmap",
    "multiprocessing.shared_memory.SharedMemory",
    "shared_memory.SharedMemory",
    "tempfile.NamedTemporaryFile", "tempfile.TemporaryFile",
    "tempfile.mkstemp",
}
# terminal names that read as resource ctors regardless of module path
# (this codebase's own lifecycled types + stdlib spellings)
_ACQUIRER_TERMINALS = {
    "SharedMemory", "NamedTemporaryFile", "RpcServer",
    "EventLoopThread", "ThreadPoolExecutor",
}
# attribute-valued ctors that spawn a background computation the class
# must cancel/join at stop (for the class-level rule only)
_SPAWNER_SUFFIXES = {
    "ensure_future", "create_task", "Thread", "background", "Timer",
}
_RELEASE_METHODS = {
    "close", "aclose", "release", "unlink", "shutdown", "stop",
    "terminate", "cancel", "join", "cleanup", "destroy",
}
_LIFECYCLE_METHODS = {
    "stop", "shutdown", "close", "aclose", "teardown", "destroy",
    "stop_all", "__exit__", "__aexit__",
}
_INIT_METHODS = {"__init__", "start", "_start"}


def _walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def _is_acquirer(call: ast.Call, imports: ImportMap) -> bool:
    resolved = imports.resolve_call(call)
    if resolved in _ACQUIRERS:
        return True
    term = terminal_attr(call.func)
    return term in _ACQUIRER_TERMINALS


def _is_spawner(call: ast.Call, imports: ImportMap) -> bool:
    if _is_acquirer(call, imports):
        return True
    term = terminal_attr(call.func)
    return term in _SPAWNER_SUFFIXES


def _release_of(node: ast.AST, name: str, imports: ImportMap) -> bool:
    """`name.close()` / `os.close(name)`-shaped release of the local."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _RELEASE_METHODS \
            and isinstance(f.value, ast.Name) and f.value.id == name:
        return True
    resolved = imports.resolve_call(node)
    if resolved in ("os.close", "os.unlink", "os.remove"):
        return any(isinstance(a, ast.Name) and a.id == name
                   for a in node.args)
    return False


def _escapes(fnode: ast.AST, name: str, imports: ImportMap) -> bool:
    """Ownership leaves the scope: returned/yielded, stored onto an
    attribute/subscript, or handed to a collection/registry call. Such
    a resource is released elsewhere by design."""
    for sub in _walk_skip_defs(fnode):
        if isinstance(sub, (ast.Global, ast.Nonlocal)):
            if name in sub.names:
                return True  # module/outer-scope lifetime by declaration
        elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
            val = sub.value
            if val is not None and any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(val)):
                return True
        elif isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                        and any(isinstance(n, ast.Name) and n.id == name
                                for n in ast.walk(sub.value)):
                    return True
        elif isinstance(sub, ast.Call) and not _release_of(
                sub, name, imports):
            # passed as an argument to anything that isn't a release:
            # transfer of ownership (registry.add(f), spawn(sock=s)...)
            # or at minimum shared custody we can't track
            for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(a, ast.Name) and a.id == name:
                    return True
    return False


def _finally_lines(fnode: ast.AST) -> Set[int]:
    lines: Set[int] = set()
    for sub in _walk_skip_defs(fnode):
        if isinstance(sub, ast.Try) and sub.finalbody:
            for stmt in sub.finalbody:
                for n in ast.walk(stmt):
                    if hasattr(n, "lineno"):
                        lines.add(n.lineno)
    return lines


def _risky_between(fnode: ast.AST, lo: int, hi: int) -> bool:
    """A raise-capable node (call/await/raise) strictly between the
    acquire line and the first release line."""
    for sub in _walk_skip_defs(fnode):
        if isinstance(sub, (ast.Call, ast.Await, ast.Raise)) \
                and lo < getattr(sub, "lineno", lo) < hi:
            return True
    return False


def _scan_function(qualname: str, fnode: ast.AST, imports: ImportMap,
                   path: str, findings: List[Finding]) -> None:
    if getattr(fnode, "name", "") == "__del__":
        return  # finalizers are the release path, not an acquire site
    fin_lines = _finally_lines(fnode)
    for stmt in _walk_skip_defs(fnode):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        val = stmt.value
        # fd, path = tempfile.mkstemp() — track the fd element only if
        # simple; give tuple unpacking a pass otherwise
        if not isinstance(tgt, ast.Name):
            continue
        if not isinstance(val, ast.Call) or not _is_acquirer(val, imports):
            continue
        name = tgt.id
        if _escapes(fnode, name, imports):
            continue
        releases = [sub for sub in _walk_skip_defs(fnode)
                    if _release_of(sub, name, imports)
                    and sub.lineno > stmt.lineno]
        ctor = dotted(val.func) or "<ctor>"
        if not releases:
            findings.append(Finding(
                PASS_NAME, "unguarded-acquire", path, stmt.lineno,
                qualname,
                f"`{name} = {ctor}(...)` is never released in this "
                "scope — a raise (or plain fall-through) strands the "
                "resource until GC",
                detail=f"never-released {name} {ctor}"))
            continue
        if any(r.lineno in fin_lines for r in releases):
            continue  # released in a finally — protected
        first_rel = min(r.lineno for r in releases)
        if _risky_between(fnode, stmt.lineno, first_rel):
            findings.append(Finding(
                PASS_NAME, "unguarded-acquire", path, stmt.lineno,
                qualname,
                f"`{name} = {ctor}(...)` is released only on the happy "
                f"path (release at line {first_rel} not in a finally); "
                "a raise in between leaks it",
                detail=f"release-not-in-finally {name} {ctor}"))


def _scan_class(cls: ast.ClassDef, imports: ImportMap, path: str,
                findings: List[Finding]) -> None:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    lifecycle = [m for name, m in methods.items()
                 if name in _LIFECYCLE_METHODS]
    if not lifecycle:
        return  # value object / externally managed — nothing to check
    # attrs the lifecycle methods (and __del__, and helpers they could
    # reach — we approximate with every non-init method) touch
    released_attrs: Set[str] = set()
    for name, m in methods.items():
        if name in _INIT_METHODS:
            continue
        for sub in ast.walk(m):
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self":
                released_attrs.add(sub.attr)
    for init_name in _INIT_METHODS:
        init = methods.get(init_name)
        if init is None:
            continue
        for stmt in _walk_skip_defs(init):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            tgt = stmt.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            val = stmt.value
            if not isinstance(val, ast.Call) \
                    or not _is_spawner(val, imports):
                continue
            if tgt.attr in released_attrs:
                continue
            ctor = dotted(val.func) or "<ctor>"
            findings.append(Finding(
                PASS_NAME, "stop-leaks-resource", path, stmt.lineno,
                f"{cls.name}.{init_name}",
                f"`self.{tgt.attr} = {ctor}(...)` is acquired here but "
                f"no lifecycle method "
                f"({'/'.join(sorted(m.name for m in lifecycle))}) ever "
                "references it — shutdown leaves it live",
                detail=f"stop-leaks self.{tgt.attr} {ctor}"))


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    imports = ImportMap(tree)
    for qualname, fnode, _cls in iter_functions(tree):
        _scan_function(qualname, fnode, imports, path, findings)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _scan_class(node, imports, path, findings)
    return findings
