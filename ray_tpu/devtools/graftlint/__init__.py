"""Graftlint — concurrency-hazard static analysis for the ray_tpu
control plane, plus a runtime lock-order witness (see witness.py).

Five AST passes over the whole package (ref: the reference Ray core
leans on C++-side TSan/ASan for these bug classes; our Python planes
get their own tooling):

  * ``blocking``   — event-loop blocking-call detector
  * ``lock-order`` — static lock-acquisition graph, cycles = deadlocks
  * ``finalizer``  — ``__del__``/weakref callbacks touching loops/RPC/locks
  * ``leak``       — unawaited coroutines, fire-and-forget tasks,
    never-joined non-daemon threads
  * ``wire``       — wire-tag registry consistency (_private/wire.py)

plus the error-plane suite (PR 8 — faults must surface as attributed
errors, never as the hangs the stall sentinel then has to chase):

  * ``swallow``     — discard-shaped exception handlers; hard errors for
    clauses that can absorb cancellation/interrupt, and for
    ``raise X`` inside ``except`` without ``from``
  * ``cleanup``     — resource acquires without try/finally or ``with``
    protection, and lifecycle methods that never release what
    ``__init__``/``start`` acquired
  * ``rpc-timeout`` — unbounded ``await ....call(...)`` and constant-
    sleep retry loops with no backoff cap or deadline

Usage (CI runs this; `cli.py lint` is the same entry point):

    python -m ray_tpu.devtools.graftlint --baseline graftlint_baseline.json
    python -m ray_tpu.devtools.graftlint --update-baseline ...

Inline suppression: ``# graftlint: ignore[pass-name]`` on the offending
line or its enclosing ``def`` line.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, List, Optional

from . import (blocking, cleanup, finalizers, leaks, lockorder,
               rpctimeout, swallow, wirecheck)
from ._astutil import iter_functions, parse_module
from .findings import Finding, Suppressions, assign_fingerprints

PASSES: Dict[str, Callable] = {
    "blocking": blocking.run,
    "lock-order": lockorder.run,
    "finalizer": finalizers.run,
    "leak": leaks.run,
    "wire": wirecheck.run,
    "swallow": swallow.run,
    "cleanup": cleanup.run,
    "rpc-timeout": rpctimeout.run,
}


def lint_source(source: str, path: str,
                select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected passes over one file's source. ``path`` is the
    repo-relative path recorded in findings."""
    tree = parse_module(source, path)
    if tree is None:
        return [Finding("parse", "syntax-error", path, 1, "<module>",
                        "file does not parse; graftlint skipped it",
                        detail="syntax-error")]
    sup = Suppressions(source)
    # enclosing-def lines also accept suppressions for their body
    def_lines: Dict[str, int] = {
        qn: fn.lineno for qn, fn, _ in iter_functions(tree)}
    out: List[Finding] = []
    for name, fn in PASSES.items():
        if select is not None and name not in select:
            continue
        for f in fn(tree, source, path):
            scope_head = f.scope.split("->")[0]
            if sup.is_suppressed(f.pass_name, f.line,
                                 def_lines.get(scope_head, -1)):
                continue
            out.append(f)
    assign_fingerprints(out)
    return out


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint .py files under the given files/directories. Findings carry
    paths relative to ``root`` (default: common prefix's dirname)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in ("__pycache__", "build",
                                            ".git")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    if root is None:
        root = os.getcwd()
    findings: List[Finding] = []
    for fp in files:
        try:
            with open(fp, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        rel = os.path.relpath(fp, root)
        findings.extend(lint_source(source, rel, select=select))
    assign_fingerprints(findings)
    return findings
