"""Finding record + inline-suppression plumbing shared by every pass.

A finding's *fingerprint* is what the baseline stores, so it must be
stable under unrelated edits: it hashes the pass, rule, file (repo-
relative), and the enclosing scope's qualified name plus a normalized
detail string — never a line number. Two identical findings in one
scope get an occurrence suffix (``#2``, ``#3``…) so a fixed one can be
removed from the baseline without masking its twin.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# trailing-comment suppression, flake8-style:
#   x = risky()  # graftlint: ignore[lock-order]
#   x = risky()  # graftlint: ignore  (all passes)
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore(?:\[(?P<passes>[\w,\- ]+)\])?")


@dataclass
class Finding:
    pass_name: str          # "blocking", "lock-order", "finalizer", ...
    rule: str               # machine id, e.g. "blocking-call-in-async"
    path: str               # repo-relative path
    line: int
    scope: str              # enclosing qualname ("Class.method") or "<module>"
    message: str            # human text; may embed line numbers freely
    detail: str = ""        # fingerprint-normalized extra (no line numbers!)
    fingerprint: str = field(default="", compare=False)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}] "
                f"{self.message}  ({self.fingerprint})")


def assign_fingerprints(findings: List[Finding]) -> None:
    seen: Dict[str, int] = {}
    for f in findings:
        base = hashlib.sha1(
            f"{f.pass_name}|{f.rule}|{f.path}|{f.scope}|{f.detail}"
            .encode()).hexdigest()[:16]
        n = seen.get(base, 0)
        seen[base] = n + 1
        f.fingerprint = base if n == 0 else f"{base}#{n + 1}"


class Suppressions:
    """Per-file map of line -> suppressed pass names (None = all)."""

    def __init__(self, source: str):
        self._by_line: Dict[int, Optional[set]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            passes = m.group("passes")
            self._by_line[i] = (
                None if passes is None
                else {p.strip() for p in passes.split(",") if p.strip()})

    def is_suppressed(self, pass_name: str, *lines: int) -> bool:
        """True if any of the given lines (the finding's own line and,
        by convention, its enclosing def's line) suppresses the pass."""
        for ln in lines:
            entry = self._by_line.get(ln, False)
            if entry is False:
                continue
            if entry is None or pass_name in entry:
                return True
        return False
