"""Shared AST plumbing for the graftlint passes.

Everything here is heuristic by design: the passes trade soundness for
a near-zero false-positive rate on *this* codebase's idioms (locks are
``self._lock``-shaped attributes or names assigned from
``threading.Lock()`` / ``locking.make_lock()``; the io loop is an
``EventLoopThread``). The fixture suite in tests/test_graftlint.py
pins both the true positives and the false-positive guards.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# attribute/name shapes that read as a mutex even without seeing the
# assignment ("_lock", "registry_lock", "_cv", "cond", "_mu"...)
_LOCKISH_RE = re.compile(
    r"(^|_)(lock|locks|mutex|mu|cv|cond|condition)$", re.IGNORECASE)

_LOCK_CTORS = {
    ("threading", "Lock"), ("threading", "RLock"),
    ("threading", "Condition"), ("threading", "Semaphore"),
    ("threading", "BoundedSemaphore"),
    ("locking", "make_lock"), ("locking", "make_rlock"),
    ("locking", "make_condition"),
}


def parse_module(source: str, path: str) -> Optional[ast.Module]:
    try:
        return ast.parse(source, filename=path)
    except SyntaxError:
        return None


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def terminal_attr(node: ast.AST) -> Optional[str]:
    """Last component of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class ImportMap:
    """module-alias resolution: `import time as _time` -> _time => time,
    `from time import sleep` -> sleep => time.sleep."""

    def __init__(self, tree: ast.Module):
        self.mod_alias: Dict[str, str] = {}   # local name -> module
        self.from_name: Dict[str, str] = {}   # local name -> "mod.attr"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_alias[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_name[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        """Canonical dotted name of the callee, imports resolved.
        `_time.sleep(...)` -> "time.sleep"; `sleep(...)` (from time
        import sleep) -> "time.sleep"."""
        name = call_name(call)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        if head in self.mod_alias:
            base = self.mod_alias[head]
            return f"{base}.{rest}" if rest else base
        if not rest and head in self.from_name:
            return self.from_name[head]
        return name


def is_lock_ctor(call: ast.Call, imports: ImportMap) -> bool:
    resolved = imports.resolve_call(call)
    if resolved is None:
        return False
    parts = resolved.split(".")
    if len(parts) < 2:
        return ("", parts[0]) in {(m, f) for m, f in _LOCK_CTORS}
    return (parts[-2], parts[-1]) in _LOCK_CTORS


@dataclass
class LockNames:
    """Names/attrs known (assignment-tracked) or presumed (shape) to be
    locks within one module."""
    assigned: Set[str] = field(default_factory=set)   # dotted exprs

    def looks_like_lock(self, expr: ast.AST) -> bool:
        name = dotted(expr)
        if name is not None and name in self.assigned:
            return True
        term = terminal_attr(expr)
        return term is not None and bool(_LOCKISH_RE.search(term))


def collect_lock_names(tree: ast.Module, imports: ImportMap) -> LockNames:
    names = LockNames()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if is_lock_ctor(node.value, imports):
                for tgt in node.targets:
                    name = dotted(tgt)
                    if name:
                        names.assigned.add(name)
    return names


class ScopeVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains the enclosing qualname ("Cls.meth")."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def scope(self) -> str:
        return ".".join(self._stack) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def iter_functions(tree: ast.Module):
    """Yield (qualname, func_node, class_node_or_None) for every def."""
    out: List[Tuple[str, ast.AST, Optional[ast.ClassDef]]] = []

    def walk(node, prefix: str, cls: Optional[ast.ClassDef]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                out.append((qn, child, cls))
                walk(child, qn + ".", cls)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", child)
            else:
                walk(child, prefix, cls)

    walk(tree, "", None)
    return out
