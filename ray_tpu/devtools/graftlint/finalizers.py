"""Pass 3 — finalizer safety.

``__del__`` and ``weakref`` finalizer callbacks run at arbitrary points
— inside another thread's allocation, during interpreter teardown, or
(the PR 1 bug class) *on the io-loop thread itself* while it drains a
callback that dropped the last reference. From there, hopping onto the
loop deadlocks, RPC may hit a torn-down transport, and lock acquisition
can self-deadlock against the frame the GC interrupted.

Flags, in a ``__del__`` body or a weakref callback (plus one hop into
same-class ``self.m()`` helpers):

  * loop hops: ``call_soon_threadsafe`` / ``run_coroutine_threadsafe``,
    or ``.run(`` / ``.spawn(`` / ``.stop(`` on a loop-ish receiver
    (name contains "loop"/"io")            -> ``finalizer-touches-loop``
  * RPC: ``.call(`` / ``.call_retrying(`` / ``.connect(``
                                           -> ``finalizer-does-rpc``
  * process kills: ``.kill(`` / ``.terminate(``  (PR 1's exact bug)
                                           -> ``finalizer-kills``
  * blocking: ``time.sleep``, ``.join(``, ``.result(``, unbounded
    ``.acquire()``, ``with <lock>:``       -> ``finalizer-blocks``

Recognized mitigation (pinned as a false-positive guard in the fixture
tests): a finalizer that consults ``sys.is_finalizing`` — the
finalization-safe pattern PR 3 established in ``Dataset.__del__`` — is
trusted to have thought this through and is skipped entirely.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ._astutil import (ImportMap, collect_lock_names, dotted,
                       iter_functions, terminal_attr)
from .findings import Finding

PASS_NAME = "finalizer"

_LOOP_HOPS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}
_LOOPISH = ("loop", "_io", "io_thread", "ioloop")
_RPC_CALLS = {"call", "call_retrying", "connect"}
_KILLS = {"kill", "terminate"}
_BLOCKING_ATTRS = {"join", "result"}


def _mentions_is_finalizing(fnode) -> bool:
    for node in ast.walk(fnode):
        if isinstance(node, ast.Attribute) and node.attr == "is_finalizing":
            return True
        if isinstance(node, ast.Name) and "is_finalizing" in node.id:
            return True
    return False


def _hazards(fnode, imports: ImportMap, locks) -> List[Tuple[int, str, str]]:
    """(line, rule, description) hazards lexically in this function."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(fnode):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if locks.looks_like_lock(item.context_expr):
                    out.append((node.lineno, "finalizer-blocks",
                                f"acquires `{dotted(item.context_expr)}`"))
        if not isinstance(node, ast.Call):
            continue
        resolved = imports.resolve_call(node)
        if resolved == "time.sleep":
            out.append((node.lineno, "finalizer-blocks", "time.sleep"))
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        attr = func.attr
        recv = (terminal_attr(func.value) or "").lower()
        where = dotted(func) or attr
        if attr in _LOOP_HOPS:
            out.append((node.lineno, "finalizer-touches-loop",
                        f"`{where}` hops onto the event loop"))
        elif attr in ("run", "spawn", "stop") and \
                (recv == "io" or any(t in recv for t in _LOOPISH)):
            out.append((node.lineno, "finalizer-touches-loop",
                        f"`{where}` targets the io loop"))
        elif attr in _RPC_CALLS and recv not in ("self",):
            out.append((node.lineno, "finalizer-does-rpc",
                        f"`{where}` issues RPC"))
        elif attr in _KILLS:
            out.append((node.lineno, "finalizer-kills",
                        f"`{where}` kills a process from a finalizer"))
        elif attr == "acquire" and locks.looks_like_lock(func.value):
            if not node.args and not node.keywords:
                out.append((node.lineno, "finalizer-blocks",
                            f"`{where}` unbounded lock acquire"))
        elif attr in _BLOCKING_ATTRS:
            out.append((node.lineno, "finalizer-blocks",
                        f"`{where}` blocks"))
    return out


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    imports = ImportMap(tree)
    locks = collect_lock_names(tree, imports)
    findings: List[Finding] = []

    funcs = iter_functions(tree)
    by_class: Dict[Optional[str], Dict[str, ast.AST]] = {}
    for qualname, fnode, cls in funcs:
        cname = cls.name if cls is not None else None
        by_class.setdefault(cname, {})[fnode.name] = fnode

    # weakref callback targets: weakref.finalize(obj, cb, ...) and
    # weakref.ref(obj, cb) — collect bare callee names
    weakref_cbs: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = imports.resolve_call(node)
            if name in ("weakref.finalize", "weakref.ref") and \
                    len(node.args) >= 2:
                cb = terminal_attr(node.args[1])
                if cb:
                    weakref_cbs.add(cb)

    def scan(qualname: str, fnode, cname: Optional[str], kind: str):
        if _mentions_is_finalizing(fnode):
            return  # finalization-guarded: the blessed pattern
        for line, rule, desc in _hazards(fnode, imports, locks):
            findings.append(Finding(
                PASS_NAME, rule, path, line, qualname,
                f"{kind} `{qualname}` {desc} — unsafe during GC/teardown",
                detail=desc))
        # one hop: self.m() helpers in the same class
        for node in ast.walk(fnode):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                helper = by_class.get(cname, {}).get(node.func.attr)
                if helper is None or _mentions_is_finalizing(helper):
                    continue
                for line, rule, desc in _hazards(helper, imports, locks):
                    findings.append(Finding(
                        PASS_NAME, rule, path, line,
                        f"{qualname}->{node.func.attr}",
                        f"{kind} `{qualname}` calls "
                        f"`{node.func.attr}`, which {desc}",
                        detail=f"via {node.func.attr}: {desc}"))

    for qualname, fnode, cls in funcs:
        cname = cls.name if cls is not None else None
        if fnode.name == "__del__":
            scan(qualname, fnode, cname, "finalizer")
        elif fnode.name in weakref_cbs:
            scan(qualname, fnode, cname, "weakref callback")
    return findings
