"""Runtime lock-order witness — TSan-lite for the Python planes.

Where the static lock-order pass sees lexical structure, the witness
sees truth: every ``WitnessLock`` records, per thread, the order locks
are actually taken, merges those orders into one global directed graph
of lock *classes* (lockdep-style: keyed by the name given at
construction, not instance identity — an AB/BA inversion observed on
different instances is the same future deadlock), and raises
``LockOrderViolation`` the moment an acquisition would close a cycle —
*before* the threads wedge, with both stacks attached: the one
acquiring now and the one that established the reverse edge.

Enabled via the ``lock_witness_enabled`` config flag
(``RAY_TPU_LOCK_WITNESS_ENABLED=1``); production builds pay a single
``if`` per lock construction (see _private/locking.py) and nothing per
acquisition.

Re-entrancy: re-acquiring a lock instance already held by this thread
never adds graph edges (that is RLock semantics' problem, and the
plain-Lock self-deadlock is caught separately as ``self-deadlock``).
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """An acquisition would close a cycle in the global lock-order
    graph. Carries the forward path and both formation stacks."""

    def __init__(self, message: str, cycle: List[str],
                 acquiring_stack: str, prior_stack: str):
        super().__init__(message)
        self.cycle = cycle
        self.acquiring_stack = acquiring_stack
        self.prior_stack = prior_stack


class _EdgeInfo:
    __slots__ = ("stack", "thread_name", "count")

    def __init__(self, stack: str, thread_name: str):
        self.stack = stack
        self.thread_name = thread_name
        self.count = 1


class LockWitness:
    """The global acquisition-order graph. One per process."""

    def __init__(self):
        # plain lock, never witnessed: guards only the graph itself
        self._mu = threading.Lock()
        self._adj: Dict[str, Set[str]] = {}
        self._edges: Dict[Tuple[str, str], _EdgeInfo] = {}
        self._tls = threading.local()
        self.violations: List[LockOrderViolation] = []

    # ---- per-thread held stack -------------------------------------
    def _held(self) -> List["WitnessLock"]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # ---- graph -----------------------------------------------------
    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """Lock-class path src -> ... -> dst, caller holds self._mu."""
        stack, seen, parent = [src], {src}, {}
        while stack:
            n = stack.pop()
            if n == dst:
                chain, cur = [dst], dst
                while cur != src:
                    cur = parent[cur]
                    chain.append(cur)
                return list(reversed(chain))
            for m in self._adj.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    parent[m] = n
                    stack.append(m)
        return None

    def before_acquire(self, lock: "WitnessLock",
                       blocking: bool = True) -> None:
        held = self._held()
        if any(h is lock for h in held):
            # non-blocking probes (Condition._is_owned fallback, try-
            # locks) are legitimate; only a blocking re-acquire wedges
            if not lock.reentrant and blocking:
                raise LockOrderViolation(
                    f"self-deadlock: thread "
                    f"{threading.current_thread().name!r} re-acquires "
                    f"non-reentrant lock class {lock.name!r} it already "
                    f"holds",
                    [lock.name, lock.name],
                    "".join(traceback.format_stack(limit=16)), "")
            return  # re-entrant re-acquire: no ordering information
        # edges from every distinct held lock CLASS to this one
        srcs = []
        seen: Set[str] = {lock.name}
        for h in held:
            if h.name not in seen:
                seen.add(h.name)
                srcs.append(h.name)
        if not srcs:
            return
        me = threading.current_thread().name
        with self._mu:
            for src in srcs:
                back = self._path(lock.name, src)
                if back is not None:
                    # closing src -> lock.name would create a cycle
                    prior = self._edges.get((back[0], back[1]))
                    now_stack = "".join(traceback.format_stack(limit=24))
                    cycle = [src] + back
                    v = LockOrderViolation(
                        "lock-order violation: acquiring "
                        f"{lock.name!r} while holding {src!r} inverts "
                        f"the established order {'→'.join(back)} "
                        f"(first taken by thread "
                        f"{prior.thread_name if prior else '?'!r})."
                        f"\n--- this thread ({me}) now:\n{now_stack}"
                        f"\n--- prior {back[0]}→{back[1]} formation "
                        f"({prior.thread_name if prior else '?'}):\n"
                        f"{prior.stack if prior else '<unrecorded>'}",
                        cycle, now_stack,
                        prior.stack if prior else "")
                    self.violations.append(v)
                    raise v
            stack = None
            for src in srcs:
                info = self._edges.get((src, lock.name))
                if info is not None:
                    info.count += 1
                    continue
                if stack is None:
                    stack = "".join(traceback.format_stack(limit=24))
                self._adj.setdefault(src, set()).add(lock.name)
                self._edges[(src, lock.name)] = _EdgeInfo(stack, me)

    def on_acquired(self, lock: "WitnessLock") -> None:
        self._held().append(lock)

    def on_release(self, lock: "WitnessLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # ---- introspection (tests, debugging) --------------------------
    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return {k: v.count for k, v in self._edges.items()}

    def edge_count(self) -> int:
        with self._mu:
            return len(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._adj.clear()
            self._edges.clear()
            self.violations.clear()


_global: Optional[LockWitness] = None
_global_mu = threading.Lock()


def global_witness() -> LockWitness:
    global _global
    if _global is None:
        with _global_mu:
            if _global is None:
                _global = LockWitness()
    return _global


class WitnessLock:
    """Drop-in threading.Lock/RLock with acquisition-order recording.

    Named: the name is the lock *class* in the witness graph — give one
    name per lock role (``"ObjectStore._lock"``), not per instance.
    """

    def __init__(self, name: str, *, reentrant: bool = False,
                 witness: Optional[LockWitness] = None):
        self.name = name
        self.reentrant = reentrant
        self._witness = witness or global_witness()
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._witness.before_acquire(self, blocking)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness.on_acquired(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._witness.on_release(self)

    def _is_owned(self) -> bool:
        # threading.Condition adopts this instead of its acquire(False)
        # probe fallback, which the witness would misread as a blocking
        # re-acquire
        return any(h is self for h in self._witness._held())

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        if inner.acquire(False):  # RLock pre-3.12 has no .locked()
            inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self.name!r} reentrant={self.reentrant}>"


def make_condition(name: str,
                   witness: Optional[LockWitness] = None
                   ) -> threading.Condition:
    """Condition whose underlying lock participates in the witness
    graph. ``wait()`` releases through the wrapper, so held-stack
    bookkeeping stays correct across waits."""
    return threading.Condition(
        WitnessLock(name, witness=witness))
