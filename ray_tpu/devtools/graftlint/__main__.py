"""CLI entry: ``python -m ray_tpu.devtools.graftlint`` (ci.sh's lint
phase, also reachable as ``cli.py lint``).

Exit codes: 0 = clean vs baseline, 1 = new findings (or any finding
with no baseline given), 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import PASSES, lint_paths
from .baseline import diff, load, save


def _default_target() -> str:
    # the installed ray_tpu package itself
    here = os.path.dirname(os.path.abspath(__file__))        # .../graftlint
    return os.path.dirname(os.path.dirname(here))            # .../ray_tpu


def _default_root() -> str:
    return os.path.dirname(_default_target())                # repo root


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="concurrency-hazard static analysis for ray_tpu")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the ray_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON; only findings not in it fail the"
                        " run (default: <repo>/graftlint_baseline.json"
                        " when present)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings and"
                        " exit 0")
    p.add_argument("--select", default=None,
                   help="comma-separated pass names "
                        f"(available: {', '.join(PASSES)})")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--list-passes", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_passes:
        for name in PASSES:
            print(name)
        return 0

    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        unknown = select - set(PASSES)
        if unknown:
            print(f"unknown pass(es): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    root = _default_root()
    paths = args.paths or [_default_target()]

    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(root, "graftlint_baseline.json")
        if os.path.exists(cand) or args.update_baseline:
            baseline_path = cand

    findings = lint_paths(paths, root=root, select=select)

    if args.update_baseline:
        if baseline_path is None:
            print("--update-baseline requires --baseline", file=sys.stderr)
            return 2
        save(baseline_path, findings)
        print(f"baseline updated: {len(findings)} finding(s) recorded in "
              f"{baseline_path}")
        return 0

    baseline = load(baseline_path) if baseline_path else {}
    new, stale = diff(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "new": [f.fingerprint for f in new],
            "stale": [e["fingerprint"] for e in stale],
        }, indent=1, default=str))
    else:
        for f in new:
            print(f.render())
        if stale:
            print(f"-- {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings;"
                  " prune with --update-baseline):")
            for e in stale:
                print(f"   {e['path']}: [{e['pass']}/{e['rule']}] "
                      f"({e['fingerprint']})")
        known = len(findings) - len(new)
        print(f"graftlint: {len(findings)} finding(s) total, "
              f"{known} baselined, {len(new)} new")
    if new:
        print("graftlint: FAIL — new concurrency hazards above; fix them"
              " or (deliberately) --update-baseline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
