"""Pass 1 — event-loop blocking-call detector.

Flags calls that wedge an asyncio loop when they run on it:

  * lexically inside ``async def`` bodies (rule
    ``blocking-call-in-async``), and
  * inside sync functions that are *only ever referenced from loop
    context* — called from async bodies or handed to
    ``call_soon``/``call_soon_threadsafe``/``call_later``/
    ``add_done_callback`` (rule ``blocking-call-on-loop``).

The blocking set: ``time.sleep``, the waiting ``subprocess`` helpers,
``socket.create_connection``, bare ``<lock>.acquire()`` (no
``blocking=False`` / ``timeout=``), ``<thread>.join()``, and
``concurrent.futures`` ``.result()`` on names that read as futures.

False-positive guards (pinned by the fixture tests):
  * subtrees handed to ``run_in_executor`` / ``asyncio.to_thread`` /
    ``Thread(target=...)`` / ``<executor>.submit`` run OFF loop — never
    flagged;
  * nested sync ``def``/``lambda`` inside an async body are separate
    functions, analyzed only via the reachability layer;
  * a sync helper with even one non-loop reference (a plain thread also
    calls it) is exempt — "reachable ONLY from io-loop callbacks".
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._astutil import (ImportMap, LockNames, collect_lock_names, dotted,
                       iter_functions, terminal_attr)
from .findings import Finding

PASS_NAME = "blocking"

_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "socket.create_connection", "socket.getaddrinfo",
}

# receiver escapes: the callable argument runs off the loop
_OFFLOAD_CALLS = {"run_in_executor", "to_thread", "submit", "Thread",
                  "start_new_thread", "map"}

_LOOP_CALLBACK_REGISTRARS = {"call_soon", "call_soon_threadsafe",
                             "call_later", "call_at", "add_done_callback",
                             "add_reader", "add_writer"}

_FUTUREISH = ("fut", "future")
_THREADISH = ("thread", "_t",)


def _is_offload_call(call: ast.Call) -> bool:
    name = terminal_attr(call.func)
    return name in _OFFLOAD_CALLS


def _blocking_reason(call: ast.Call, imports: ImportMap,
                     locks: LockNames) -> Optional[str]:
    """Why this call blocks, or None."""
    resolved = imports.resolve_call(call)
    if resolved in _BLOCKING_CALLS:
        return resolved
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr == "acquire" and locks.looks_like_lock(func.value):
        # non-blocking / bounded acquires are fine on the loop
        for kw in call.keywords:
            if kw.arg in ("blocking", "timeout"):
                return None
        if call.args:  # positional blocking=False / timeout
            return None
        return f"{dotted(func) or attr}() [unbounded lock acquire]"
    if attr == "join":
        recv = terminal_attr(func.value)
        if recv and any(t in recv.lower() for t in _THREADISH):
            return f"{dotted(func) or attr}() [thread join]"
    if attr == "result":
        recv = terminal_attr(func.value)
        if recv and any(t in recv.lower() for t in _FUTUREISH):
            return f"{dotted(func) or attr}() [blocking future wait]"
    return None


class _FuncInfo:
    __slots__ = ("qualname", "node", "is_async", "loop_refs", "other_refs")

    def __init__(self, qualname: str, node, is_async: bool):
        self.qualname = qualname
        self.node = node
        self.is_async = is_async
        self.loop_refs: int = 0     # references from loop context
        self.other_refs: int = 0    # references from anywhere else


def _scan_body(func_node, imports: ImportMap, locks: LockNames):
    """Yield (call, reason) for blocking calls lexically in this
    function's own body — skipping nested defs/lambdas and offloaded
    subtrees."""
    results: List[Tuple[ast.Call, str]] = []

    def walk(node, offloaded: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue  # separate function; reachability layer's job
            child_off = offloaded
            if isinstance(child, ast.Call):
                if not offloaded and not _is_offload_call(child):
                    reason = _blocking_reason(child, imports, locks)
                    if reason is not None:
                        results.append((child, reason))
                if _is_offload_call(child):
                    child_off = True
            walk(child, child_off)

    walk(func_node, False)
    return results


def _local_target(node: ast.AST) -> Optional[str]:
    """Name that may refer to a function in THIS module: a bare Name or
    a `self.<attr>`. `self.loop.stop` / `writer.close` never resolve
    locally — bare-name matching on those drowns the pass in FPs."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    imports = ImportMap(tree)
    locks = collect_lock_names(tree, imports)
    findings: List[Finding] = []

    funcs: Dict[str, _FuncInfo] = {}
    by_bare_name: Dict[str, List[_FuncInfo]] = {}
    for qualname, node, _cls in iter_functions(tree):
        info = _FuncInfo(qualname, node,
                         isinstance(node, ast.AsyncFunctionDef))
        funcs[qualname] = info
        by_bare_name.setdefault(node.name, []).append(info)

    # ---- layer 1: blocking calls lexically inside async bodies
    for info in funcs.values():
        if not info.is_async:
            continue
        for call, reason in _scan_body(info.node, imports, locks):
            findings.append(Finding(
                PASS_NAME, "blocking-call-in-async", path, call.lineno,
                info.qualname,
                f"blocking call `{reason}` inside `async def "
                f"{info.node.name}` wedges the event loop",
                detail=reason))

    # ---- layer 2: sync functions reachable only from loop context
    # Collect reference sites: (referencing qualname or None for module
    # level, referenced bare name, via_callback_registrar)
    refs: List[Tuple[Optional[str], str, bool]] = []

    def collect_refs(node, owner: Optional[str]):
        for child in ast.iter_child_nodes(node):
            child_owner = owner
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = child.name if owner is None else f"{owner}.{child.name}"
                # match qualnames produced by iter_functions
                for info in by_bare_name.get(child.name, ()):
                    if info.node is child:
                        qn = info.qualname
                child_owner = qn
            elif isinstance(child, ast.Call):
                nm = _local_target(child.func)
                if nm:
                    refs.append((owner, nm, False))
                registrar = terminal_attr(child.func)
                if registrar in _LOOP_CALLBACK_REGISTRARS:
                    for arg in list(child.args) + \
                            [kw.value for kw in child.keywords]:
                        nm = _local_target(arg)
                        if nm:
                            refs.append((owner, nm, True))
            collect_refs(child, child_owner)

    collect_refs(tree, None)

    # fixpoint: loop_ctx = async defs ∪ callback targets ∪ sync funcs
    # whose every reference comes from loop_ctx members
    loop_ctx: Set[str] = {qn for qn, i in funcs.items() if i.is_async}
    for owner, nm, via_cb in refs:
        if via_cb:
            for info in by_bare_name.get(nm, ()):
                loop_ctx.add(info.qualname)
    changed = True
    while changed:
        changed = False
        for info in funcs.values():
            if info.qualname in loop_ctx or info.is_async:
                continue
            in_loop = 0
            outside = 0
            for owner, nm, _via in refs:
                if nm != info.node.name:
                    continue
                if owner is not None and owner in loop_ctx:
                    in_loop += 1
                else:
                    outside += 1
            if in_loop > 0 and outside == 0:
                loop_ctx.add(info.qualname)
                changed = True

    for qn in sorted(loop_ctx):
        info = funcs[qn]
        if info.is_async:
            continue
        for call, reason in _scan_body(info.node, imports, locks):
            findings.append(Finding(
                PASS_NAME, "blocking-call-on-loop", path, call.lineno,
                info.qualname,
                f"blocking call `{reason}` in `{info.node.name}`, which "
                f"is reachable only from io-loop context",
                detail=reason))
    return findings
