"""Pass 4 — leak pass: work that silently disappears.

  * ``unawaited-coroutine`` — a bare expression-statement call to an
    ``async def`` defined in the same module: the coroutine object is
    created and dropped, the body never runs (RuntimeWarning at GC, and
    only if you're lucky).
  * ``fire-and-forget-task`` — ``asyncio.create_task`` /
    ``ensure_future`` whose return value is discarded: the event loop
    keeps only weak task references, so the task can be garbage-
    collected mid-await (observed in this repo as spurious
    ``GeneratorExit`` under GC pressure — see EventLoopThread.spawn),
    and its exception is never retrieved.
  * ``thread-never-joined`` — a non-daemon ``threading.Thread`` whose
    name is never ``.join()``-ed anywhere in the module and never
    demoted to daemon: it pins interpreter shutdown forever.

False-positive guards (fixture-pinned): awaited/assigned/gathered
coroutines; tasks kept in a variable or collection
(``self._tasks.add(asyncio.create_task(...))``); ``daemon=True``
threads; threads joined under any code path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ._astutil import ImportMap, dotted, iter_functions, terminal_attr
from .findings import Finding

PASS_NAME = "leak"

_SPAWNERS = {"create_task", "ensure_future"}


def run(tree: ast.Module, source: str, path: str) -> List[Finding]:
    imports = ImportMap(tree)
    findings: List[Finding] = []

    # resolution is deliberately narrow (the FP guard): a bare-Name call
    # resolves only to a module-level async def; a `self.m()` call only
    # to an async method of the ENCLOSING class. `writer.close()` never
    # matches an unrelated async `close` elsewhere in the module.
    funcs = iter_functions(tree)
    module_async: Set[str] = {
        fn.name for qn, fn, cls in funcs
        if isinstance(fn, ast.AsyncFunctionDef) and cls is None
        and "." not in qn}
    class_async: Dict[str, Set[str]] = {}
    for qn, fn, cls in funcs:
        if cls is not None and isinstance(fn, ast.AsyncFunctionDef):
            class_async.setdefault(cls.name, set()).add(fn.name)
    cls_of_scope: Dict[str, Optional[str]] = {
        qn: (cls.name if cls is not None else None) for qn, fn, cls in funcs}

    scopes = [("<module>", tree)] + [(qn, fn) for qn, fn, _ in funcs]

    for scope_name, scope_node in scopes:
        body_nodes = list(ast.iter_child_nodes(scope_node))
        for node in ast.walk(scope_node):
            if not isinstance(node, ast.Expr) or \
                    not isinstance(node.value, ast.Call):
                continue
            # attribute Expr statements inside nested defs belong to the
            # nested scope; only report once, for the innermost scope
            if not _owns(scope_node, node, scopes):
                continue
            call = node.value
            callee = terminal_attr(call.func)
            if callee in _SPAWNERS:
                findings.append(Finding(
                    PASS_NAME, "fire-and-forget-task", path, node.lineno,
                    scope_name,
                    f"`{dotted(call.func) or callee}(...)` result discarded:"
                    " the loop holds only weak task refs — the task can be"
                    " GC'd mid-await and its exception is never retrieved",
                    detail=f"discarded {callee}"))
            else:
                is_coro_call = False
                if isinstance(call.func, ast.Name):
                    is_coro_call = call.func.id in module_async
                elif (isinstance(call.func, ast.Attribute)
                      and isinstance(call.func.value, ast.Name)
                      and call.func.value.id == "self"):
                    own_cls = cls_of_scope.get(scope_name)
                    is_coro_call = call.func.attr in \
                        class_async.get(own_cls or "", ())
                if is_coro_call:
                    findings.append(Finding(
                        PASS_NAME, "unawaited-coroutine", path,
                        node.lineno, scope_name,
                        f"coroutine `{callee}(...)` is never awaited —"
                        " the body never runs",
                        detail=f"unawaited {callee}"))

    # ---- non-daemon threads never joined
    joined: Set[str] = set()
    daemoned: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            name = dotted(node.func.value)
            if name:
                joined.add(name)
        if isinstance(node, ast.Assign) and \
                isinstance(node.targets[0], ast.Attribute) and \
                node.targets[0].attr == "daemon":
            name = dotted(node.targets[0].value)
            if name:
                daemoned.add(name)

    # innermost enclosing function per node, for stable fingerprints
    owner_of: Dict[int, str] = {}
    for qualname, fnode, _cls in iter_functions(tree):
        for sub in ast.walk(fnode):
            owner_of[id(sub)] = qualname  # later (inner) defs overwrite

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if imports.resolve_call(call) != "threading.Thread":
            continue
        is_daemon = any(
            kw.arg == "daemon" and
            isinstance(kw.value, ast.Constant) and kw.value.value
            for kw in call.keywords)
        if is_daemon:
            continue
        target = dotted(node.targets[0])
        if target and (target in joined or target in daemoned):
            continue
        findings.append(Finding(
            PASS_NAME, "thread-never-joined", path, node.lineno,
            owner_of.get(id(node), "<module>"),
            f"non-daemon thread `{target or '<expr>'}` is never"
            " joined or made daemon — it pins interpreter shutdown",
            detail=f"thread {target or '<expr>'}"))
    return findings


def _owns(scope_node, node, scopes) -> bool:
    """True if `node` belongs lexically to `scope_node` and not to a
    nested function scope inside it."""
    target_funcs = [s for _, s in scopes if s is not scope_node]

    def contains(root, needle, stop_at_funcs) -> bool:
        for child in ast.iter_child_nodes(root):
            if child is needle:
                return True
            if stop_at_funcs and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
                continue
            if contains(child, needle, stop_at_funcs):
                return True
        return False

    del target_funcs
    return contains(scope_node, node, True)
