"""Developer tooling for the ray_tpu codebase (not part of the runtime).

Everything under this package is import-safe without jax/np — the lint
runs in CI before the native build, so it must not drag the framework in.
"""
