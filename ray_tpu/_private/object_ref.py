"""ObjectRef: a distributed future (ref: python/ray/includes/object_ref.pxi).

Reduces to (ObjectID, owner_address) on serialization; deserializing inside a
worker registers a borrowed reference with that process's core worker (the
borrower half of the distributed ref-counting protocol,
ref: src/ray/core_worker/reference_count.h:66).
"""

from __future__ import annotations

from typing import Optional

from .ids import ObjectID

# set by core_worker on init; avoids import cycle
_ref_registry = None


def _set_ref_registry(registry):
    global _ref_registry
    _ref_registry = registry


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str = "", *, _register: bool = True):
        self._id = object_id
        self._owner_address = owner_address
        if _register and _ref_registry is not None:
            _ref_registry.add_local_ref(object_id)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self) -> str:
        return self._owner_address

    def task_id(self):
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        if _ref_registry is not None:
            try:
                _ref_registry.remove_local_ref(self._id)
            except Exception:
                pass

    def __reduce__(self):
        return (_deserialize_ref, (self._id, self._owner_address))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        if _ref_registry is None:
            raise RuntimeError("ray_tpu not initialized")
        return _ref_registry.as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _deserialize_ref(object_id: ObjectID, owner_address: str) -> "ObjectRef":
    ref = ObjectRef(object_id, owner_address, _register=False)
    if _ref_registry is not None:
        _ref_registry.add_borrowed_ref(object_id, owner_address)
    return ref


class ObjectRefGenerator:
    """Iterator over the ObjectRefs a streaming task yields, in yield order
    (ref: python/ray/_raylet.pyx ObjectRefGenerator; items are reported
    eagerly by the executor and consumed with backpressure acks)."""

    def __init__(self, task_id, core):
        self._task_id = task_id
        self._core = core

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        ref = self._core.next_stream_item(self._task_id, timeout=None)
        if ref is None:
            raise StopIteration
        return ref

    def completed(self) -> bool:
        return self._core.stream_completed(self._task_id)

    def close(self) -> None:
        """Drop the owner-side stream state. An abandoned generator would
        otherwise pin its queue (and any unconsumed items) forever."""
        self._core.release_stream(self._task_id)

    @property
    def task_id(self):
        return self._task_id

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()})"
