"""Versioned wire schemas for the control plane (N16).

TPU-native analog of the reference's protobuf surface
(ref: src/ray/protobuf/*.proto — 22 files define every RPC frame, GCS
table record and journal entry). This module is the single place the
framework's on-the-wire layout lives:

  * **RPC frames** (ray_tpu/_private/rpc.py) are a msgpack envelope
    ``[WIRE_VERSION, msg_id, kind, method, body]`` — no pickle in the
    frame layer, so a native (C++/other-language) peer can speak the
    protocol by implementing this file's tables.
  * **Framework types** cross as msgpack extension records with stable
    tags (the "message structs"): ids, TaskSpec/TaskArg, ResourceSet,
    scheduling strategies, GCS info records, known exceptions.
  * **Application payloads** (user args/returns, arbitrary objects
    inside handler dicts) fall back to a tagged pickle extension
    (EXT_PICKLE) — exactly the reference's split, where protobuf
    envelopes carry pickled app bytes in ``bytes`` fields. Framework
    control messages never need the fallback.
  * **GCS journal** records are ``[WIRE_VERSION, op, ns, key, val]``
    msgpack arrays behind a little-endian u32 length; a journal whose
    records are legacy pickle (version 0, pre-schema) is still replayed
    — see ``journal_decode`` — which is the version-migration path.

Version policy: WIRE_VERSION bumps on any breaking layout change; a
receiver seeing a newer major version rejects the frame loudly instead
of misparsing it.
"""

from __future__ import annotations

import pickle
from dataclasses import fields, is_dataclass
from typing import Any, Callable, Dict, Tuple, Type

import msgpack

WIRE_VERSION = 1

# --- msgpack extension tags (stable wire contract; never reuse) ---
EXT_ID = 1          # framework id: (class_tag:u8)(raw bytes)
EXT_STRUCT = 2      # registered struct: msgpack([tag, [field values...]])
EXT_EXC = 3         # known exception: msgpack([tag, [args...]])
EXT_TUPLE = 4       # python tuple (msgpack arrays decode as lists)
EXT_SET = 5         # python set
EXT_PICKLE = 127    # app-payload escape hatch (documented, tagged)


class WireError(Exception):
    pass


# ---------------------------------------------------------------- registry

_ID_CLASSES: Dict[int, Type] = {}
_ID_TAGS: Dict[Type, int] = {}
_STRUCTS: Dict[int, Tuple[Type, Tuple[str, ...]]] = {}
_STRUCT_TAGS: Dict[Type, int] = {}
_EXCEPTIONS: Dict[int, Type] = {}
_EXC_TAGS: Dict[Type, int] = {}


def register_id(tag: int, cls: Type) -> None:
    _ID_CLASSES[tag] = cls
    _ID_TAGS[cls] = tag


def register_struct(tag: int, cls: Type,
                    field_names: Tuple[str, ...] = ()) -> None:
    """Dataclass-like record: encoded as its field values, positionally.
    APPEND new fields only (decode tolerates short records by letting
    dataclass defaults fill the tail) — that is the schema-evolution
    rule, the analog of proto field numbering."""
    if not field_names and is_dataclass(cls):
        field_names = tuple(f.name for f in fields(cls))
    _STRUCTS[tag] = (cls, field_names)
    _STRUCT_TAGS[cls] = tag


def register_exception(tag: int, cls: Type) -> None:
    _EXCEPTIONS[tag] = cls
    _EXC_TAGS[cls] = tag


def _register_all() -> None:
    from . import ids as _ids
    from . import task_spec as _ts
    from .. import exceptions as _exc

    register_id(1, _ids.JobID)
    register_id(2, _ids.NodeID)
    register_id(3, _ids.WorkerID)
    register_id(4, _ids.ActorID)
    register_id(5, _ids.TaskID)
    register_id(6, _ids.ObjectID)
    register_id(7, _ids.PlacementGroupID)

    register_struct(1, _ts.TaskArg)
    register_struct(2, _ts.FunctionDescriptor)
    register_struct(3, _ts.TaskSpec)
    register_struct(4, _ts.DefaultSchedulingStrategy)
    register_struct(5, _ts.SpreadSchedulingStrategy)
    register_struct(6, _ts.NodeAffinitySchedulingStrategy)
    register_struct(7, _ts.PlacementGroupSchedulingStrategy)
    register_struct(8, _ts.SliceSchedulingStrategy)
    register_struct(11, _ts.In, ("values",))
    register_struct(12, _ts.NotIn, ("values",))
    register_struct(13, _ts.Exists)
    register_struct(14, _ts.DoesNotExist)
    register_struct(15, _ts.NodeLabelSchedulingStrategy)

    from . import gcs as _gcs

    register_struct(9, _gcs.NodeInfo)
    register_struct(10, _gcs.ActorInfo)

    from . import blackbox as _bb

    register_struct(16, _bb.CrashBundleInfo)
    register_struct(17, _bb.ObsCheckpointInfo)

    # train goodput plane (ray_tpu/train/telemetry.py is stdlib-only and
    # the train package lazy-loads its jax-heavy step factory, so this
    # stays cheap in every process)
    from ..train import telemetry as _tt

    register_struct(18, _tt.TrainStepTelemetry)
    register_struct(19, _tt.TrainJobLedger)

    register_exception(1, _exc.RayTpuError)
    register_exception(2, _exc.TaskError)
    register_exception(3, _exc.TaskCancelledError)
    register_exception(4, _exc.WorkerCrashedError)
    register_exception(5, _exc.ObjectLostError)
    register_exception(6, _exc.GetTimeoutError)
    register_exception(7, _exc.ActorDiedError)
    register_exception(8, _exc.CollectiveTimeoutError)


_registered = False


def _ensure_registered() -> None:
    global _registered
    if not _registered:
        _registered = True
        _register_all()


# ---------------------------------------------------------------- encoding

def _default(obj: Any):
    _ensure_registered()
    t = type(obj)
    tag = _ID_TAGS.get(t)
    if tag is not None:
        return msgpack.ExtType(EXT_ID, bytes([tag]) + obj.binary())
    tag = _STRUCT_TAGS.get(t)
    if tag is not None:
        names = _STRUCTS[tag][1]
        vals = [getattr(obj, n) for n in names]
        return msgpack.ExtType(EXT_STRUCT, _pack([tag, vals]))
    if t is tuple:
        return msgpack.ExtType(EXT_TUPLE, _pack(list(obj)))
    if t is set or t is frozenset:
        return msgpack.ExtType(EXT_SET, _pack(list(obj)))
    from .task_spec import ResourceSet

    if t is ResourceSet:
        return msgpack.ExtType(EXT_STRUCT, _pack([100, [obj.to_dict()]]))
    if isinstance(obj, BaseException):
        tag = _EXC_TAGS.get(t)
        if tag is not None:
            try:
                return msgpack.ExtType(EXT_EXC, _pack([tag, list(obj.args)]))
            except Exception:
                pass
        # unknown/unpacked exception (user-defined, chained state):
        # tagged pickle fallback, same as app payloads
    return msgpack.ExtType(EXT_PICKLE, pickle.dumps(obj, protocol=5))


def _ext_hook(code: int, data: bytes):
    _ensure_registered()
    if code == EXT_ID:
        cls = _ID_CLASSES.get(data[0])
        if cls is None:
            raise WireError(f"unknown id tag {data[0]}")
        return cls(data[1:])
    if code == EXT_STRUCT:
        tag, vals = _unpack(data)
        if tag == 100:
            from .task_spec import ResourceSet

            return ResourceSet(vals[0])
        entry = _STRUCTS.get(tag)
        if entry is None:
            raise WireError(f"unknown struct tag {tag}")
        cls, names = entry
        # forward-compat both ways: extra trailing values (newer peer)
        # are dropped; missing ones (older peer) take field defaults
        kwargs = {n: v for n, v in zip(names, vals)}
        return cls(**kwargs)
    if code == EXT_EXC:
        tag, args = _unpack(data)
        cls = _EXCEPTIONS.get(tag)
        if cls is None:
            raise WireError(f"unknown exception tag {tag}")
        try:
            return cls(*args)
        except TypeError:
            e = Exception(*args)
            e.__class__ = cls  # arg-shape drift: still the right type
            return e
    if code == EXT_TUPLE:
        return tuple(_unpack(data))
    if code == EXT_SET:
        return set(_unpack(data))
    if code == EXT_PICKLE:
        return pickle.loads(data)
    raise WireError(f"unknown extension code {code}")


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, default=_default, use_bin_type=True,
                         strict_types=True)


def _unpack(data) -> Any:
    return msgpack.unpackb(data, ext_hook=_ext_hook, raw=False,
                           strict_map_key=False)


# ---------------------------------------------------------------- frames

def encode_frame(msg_id: int, kind: int, method: str, payload: Any) -> bytes:
    """One RPC frame body (the [u32 len] prefix is the transport's)."""
    return _pack([WIRE_VERSION, msg_id, kind, method, payload])


def decode_frame(body) -> Tuple[int, int, str, Any]:
    if body[:1] == b"\x80":  # pickle protocol-2+ magic: a v0 peer
        msg_id, kind, method, payload = pickle.loads(body)
        return msg_id, kind, method, payload
    frame = _unpack(body)
    version = frame[0]
    if version > WIRE_VERSION:
        raise WireError(
            f"peer speaks wire version {version}, this build supports "
            f"<= {WIRE_VERSION}")
    return frame[1], frame[2], frame[3], frame[4]


# ---------------------------------------------------------------- journal

def journal_encode(op: str, ns: str, key: str, val) -> bytes:
    return _pack([WIRE_VERSION, op, ns, key, val])


def journal_decode(body) -> Tuple[str, str, str, Any]:
    """Decode one journal record; legacy (version-0) records are raw
    pickled (op, ns, key, val) tuples — replaying them transparently is
    the journal's version-migration path (a restart compacts the
    journal, rewriting every record at the current version)."""
    if body[:1] == b"\x80":
        op, ns, key, val = pickle.loads(body)
        return op, ns, key, val
    rec = _unpack(body)
    if rec[0] > WIRE_VERSION:
        raise WireError(f"journal record version {rec[0]} unsupported")
    return rec[1], rec[2], rec[3], rec[4]
