"""Black-box flight recorder: per-process flight rings + crash bundles.

Every long-lived process (driver, worker, raylet, GCS) owns a
``FlightRecorder`` — a bounded in-memory ring of the last N cluster
events, log records, ambient stack samples, metric snapshots and
in-flight task/request ids. The ring is flushed to a per-process
*flight file* in the session dir on a slow background tick, and is
promoted to a versioned *crash bundle* either by the dying process
itself (SIGTERM/SIGABRT handlers, ``faulthandler`` for SIGSEGV, atexit
on an unclean interpreter exit) or — for deaths no handler can see
(SIGKILL, OOM-kill, machine loss) — by a survivor sweeping the corpse's
flight file when the raylet/GCS detects the death (worker disconnect,
heartbeat loss). The reference has no analog below the event log; the
design follows the flight-data-recorder shape MegaScale describes for
after-the-fact forensics of processes that are already gone
(PAPERS.md), and `cli postmortem` is the reader.

Layout under ``<session_dir>/blackbox/``:

    flight/<role>-<pid>.json        live flight ring, rewritten each tick
    bundles/<role>-<pid>-<ms>.json  promoted crash bundles (versioned)
    fault-<role>-<pid>.log          faulthandler C-level tracebacks
    events.jsonl                    the GCS's persisted event journal
    incidents/<ms>/                 self-diagnosis artifacts (profile
                                    burst, stack sweep, memory report)
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

BUNDLE_VERSION = 1

_BLACKBOX_DIRNAME = "blackbox"
_FLIGHT_DIRNAME = "flight"
_BUNDLE_DIRNAME = "bundles"
_INCIDENT_DIRNAME = "incidents"
_EVENTS_JOURNAL = "events.jsonl"


def blackbox_dir(session_dir: str) -> str:
    return os.path.join(session_dir, _BLACKBOX_DIRNAME)


def flight_dir(session_dir: str) -> str:
    return os.path.join(blackbox_dir(session_dir), _FLIGHT_DIRNAME)


def bundle_dir(session_dir: str) -> str:
    return os.path.join(blackbox_dir(session_dir), _BUNDLE_DIRNAME)


def incident_dir(session_dir: str) -> str:
    return os.path.join(blackbox_dir(session_dir), _INCIDENT_DIRNAME)


def events_journal_path(session_dir: str) -> str:
    return os.path.join(blackbox_dir(session_dir), _EVENTS_JOURNAL)


# ------------------------------------------------------------ wire records
# RPC-visible summaries (cli/state API rows; the full bundle JSON never
# rides the control plane — only these). Registered in wire.py as struct
# tags 16/17; append fields only (schema-evolution rule).

@dataclass
class CrashBundleInfo:
    """One crash bundle, as listed over the state API."""
    role: str = ""
    pid: int = 0
    node_id: str = ""
    reason: str = ""
    signal_name: str = ""
    bundled_at: float = 0.0
    written_at: float = 0.0
    path: str = ""
    inflight: list = field(default_factory=list)


@dataclass
class ObsCheckpointInfo:
    """Durable-observability checkpoint metadata (GCS restart handoff)."""
    version: int = BUNDLE_VERSION
    written_at: float = 0.0
    series: int = 0
    slo_specs: int = 0
    task_events: int = 0
    metrics: int = 0


# ---------------------------------------------------------- flight recorder

class FlightRecorder:
    """Bounded flight ring for one process, flushed to a flight file.

    Ring appends are lock-guarded deque ops (O(1), off any hot path —
    events/logs only); the flush thread serializes the ring every
    ``flush_interval_s`` so a SIGKILL'd corpse still leaves a recent
    snapshot for the survivor sweep. Providers are called only at flush
    or dump time, never per-append.
    """

    def __init__(self, role: str, session_dir: str, *,
                 ident: str = "", node_id: str = "",
                 ring_size: int = 256, flush_interval_s: float = 2.0,
                 inflight_provider: Optional[Callable[[], list]] = None,
                 stacks_provider: Optional[Callable[[], Any]] = None,
                 metrics_provider: Optional[Callable[[], Any]] = None):
        self.role = role
        self.session_dir = session_dir
        self.ident = ident
        self.node_id = node_id
        self.pid = os.getpid()
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=ring_size)
        self._logs: deque = deque(maxlen=ring_size)
        self._notes: Dict[str, Any] = {}
        self._inflight_provider = inflight_provider
        self._stacks_provider = stacks_provider
        self._metrics_provider = metrics_provider
        self._flush_interval_s = max(0.2, flush_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dumped = False
        self._closed = False
        os.makedirs(flight_dir(session_dir), exist_ok=True)
        os.makedirs(bundle_dir(session_dir), exist_ok=True)

    # ---- ring appends (cheap, any thread) ----
    def record_event(self, record: dict) -> None:
        with self._lock:
            self._events.append(record)

    def record_log(self, line: str) -> None:
        with self._lock:
            self._logs.append(line)

    def note(self, key: str, value: Any) -> None:
        """Small sticky annotations (current request id, job id, ...)."""
        with self._lock:
            if value is None:
                self._notes.pop(key, None)
            else:
                self._notes[key] = value

    # ---- snapshot / flush ----
    def _call(self, provider):
        if provider is None:
            return None
        try:
            return provider()
        except Exception as e:  # a broken provider must not kill a flush
            return {"error": repr(e)}

    def snapshot(self) -> dict:
        with self._lock:
            events = list(self._events)
            logs = list(self._logs)
            notes = dict(self._notes)
        return {
            "version": BUNDLE_VERSION,
            "role": self.role,
            "pid": self.pid,
            "node_id": self.node_id,
            "ident": self.ident,
            "started_at": self.started_at,
            "written_at": time.time(),
            "notes": notes,
            "events": events,
            "logs": logs,
            "inflight": self._call(self._inflight_provider) or [],
            "stacks": self._call(self._stacks_provider),
            "metrics": self._call(self._metrics_provider),
        }

    @property
    def flight_path(self) -> str:
        return os.path.join(flight_dir(self.session_dir),
                            f"{self.role}-{self.pid}.json")

    def flush(self) -> None:
        try:
            _write_json_atomic(self.flight_path, self.snapshot())
        except Exception:  # graftlint: ignore[swallow] — disk-full etc:
            pass  # the in-memory ring itself remains the record

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._flush_interval_s):
            self.flush()

    def start(self) -> "FlightRecorder":
        self.flush()  # a flight file exists from t=0, not first tick
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name=f"ray_tpu_blackbox_{self.role}")
        self._thread.start()
        _register(self)
        return self

    # ---- bundle promotion / teardown ----
    def dump_bundle(self, reason: str,
                    signal_name: str = "") -> Optional[str]:
        """Promote the ring to a crash bundle (idempotent per process
        death — the first cause wins)."""
        if self._dumped:
            return None
        self._dumped = True
        snap = self.snapshot()
        snap["reason"] = reason
        snap["signal"] = signal_name
        snap["bundled_at"] = time.time()
        snap["bundled_by"] = f"{self.role}-{self.pid}"
        path = os.path.join(
            bundle_dir(self.session_dir),
            f"{self.role}-{self.pid}-{int(snap['bundled_at'] * 1000)}.json")
        try:
            _write_json_atomic(path, snap)
        except Exception:  # graftlint: ignore[swallow] — dying process:
            return None  # a failed bundle write must not mask the exit
        try:
            os.unlink(self.flight_path)  # promoted: no double sweep
        except OSError:
            pass
        return path

    def close(self, clean: bool = True) -> None:
        """Stop flushing; a clean close removes the flight file so the
        survivor sweep never bundles a graceful exit."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        _unregister(self)
        if clean:
            try:
                os.unlink(self.flight_path)
            except OSError:
                pass
        else:
            self.flush()


class RingLogHandler(logging.Handler):
    """logging → flight ring bridge (last N formatted records)."""

    def __init__(self, recorder: FlightRecorder,
                 level: int = logging.INFO):
        super().__init__(level=level)
        self._recorder = recorder
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record_log(self.format(record))
        except Exception:  # graftlint: ignore[swallow] — a log handler
            pass  # must never raise into the caller's logging call


# ------------------------------------------------ process-level hooks

_recorders: List[FlightRecorder] = []
_recorders_lock = threading.Lock()
_hooks_installed = False
_fault_file = None
_prev_handlers: Dict[int, Any] = {}


def _register(recorder: FlightRecorder) -> None:
    with _recorders_lock:
        _recorders.append(recorder)
    _install_process_hooks(recorder.session_dir, recorder.role)


def _unregister(recorder: FlightRecorder) -> None:
    with _recorders_lock:
        try:
            _recorders.remove(recorder)
        except ValueError:
            pass


def recorders() -> List[FlightRecorder]:
    with _recorders_lock:
        return list(_recorders)


def dump_all(reason: str, signal_name: str = "") -> List[str]:
    out = []
    for rec in recorders():
        path = rec.dump_bundle(reason, signal_name)
        if path:
            out.append(path)
    return out


def _on_signal(signum, frame):
    name = signal.Signals(signum).name
    dump_all(f"signal:{name}", name)
    # restore the pre-install disposition and re-deliver so the exit
    # status stays what the sender expects (killed-by-signal)
    prev = _prev_handlers.get(signum, signal.SIG_DFL)
    try:
        signal.signal(signum, prev if callable(prev) or prev in (
            signal.SIG_DFL, signal.SIG_IGN) else signal.SIG_DFL)
    except (ValueError, OSError, TypeError):
        pass
    if callable(prev) and prev not in (signal.default_int_handler,):
        try:
            prev(signum, frame)
            return
        except Exception:  # graftlint: ignore[swallow] — a broken prior
            pass  # handler must not stop the re-delivery below
    os.kill(os.getpid(), signum)


def _on_atexit():
    # normal interpreter exit after close(clean=True) is a no-op (the
    # registry is empty); recorders still registered here belong to a
    # process dying without a graceful shutdown — bundle them
    if recorders():
        dump_all("atexit")


def _install_process_hooks(session_dir: str, role: str) -> None:
    """Once per process: faulthandler file for C-level deaths
    (SIGSEGV/SIGFPE/SIGBUS), Python handlers for the catchable abnormal
    exits (SIGTERM/SIGABRT), and an atexit bundle for unclean exits.
    Signal installation silently degrades off the main thread (raylet/
    GCS run inside a node's event-loop thread; the sweep path covers
    them)."""
    global _hooks_installed, _fault_file
    if _hooks_installed:
        return
    _hooks_installed = True
    try:
        os.makedirs(blackbox_dir(session_dir), exist_ok=True)
        _fault_file = open(
            os.path.join(blackbox_dir(session_dir),
                         f"fault-{role}-{os.getpid()}.log"), "w")
        faulthandler.enable(file=_fault_file)
    except Exception:
        _fault_file = None
    atexit.register(_on_atexit)
    for sig in (signal.SIGTERM, signal.SIGABRT):
        try:
            _prev_handlers[sig] = signal.getsignal(sig)
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread / restricted env


def reset_for_tests() -> None:
    """Drop process-level state so one pytest process can host many
    recorder lifecycles (hooks re-arm on the next start())."""
    global _hooks_installed, _fault_file
    with _recorders_lock:
        _recorders.clear()
    _hooks_installed = False
    if _fault_file is not None:
        try:
            faulthandler.disable()
            _fault_file.close()
        except Exception:  # graftlint: ignore[swallow] — test-only
            pass  # teardown; a closed file is fine either way
        _fault_file = None


# ------------------------------------------------------------- survivors

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def discard_flight(session_dir: str, pid: int) -> None:
    """An expected exit (graceful worker shutdown) leaves no corpse."""
    d = flight_dir(session_dir)
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        if name.endswith(f"-{pid}.json"):
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass


def sweep(session_dir: str, *, reason: str, bundled_by: str,
          pids: Optional[List[int]] = None,
          node_id: Optional[str] = None,
          require_dead: bool = True) -> List[dict]:
    """Promote dead processes' flight files into crash bundles.

    Called by the raylet on worker disconnect (``pids``) and by the GCS
    on heartbeat loss (``node_id`` — every corpse on the dead node).
    Returns the promoted bundle dicts (with ``path`` set) so the caller
    can emit events naming the in-flight work.
    """
    fdir = flight_dir(session_dir)
    try:
        names = sorted(os.listdir(fdir))
    except OSError:
        return []
    if names:
        os.makedirs(bundle_dir(session_dir), exist_ok=True)
    promoted = []
    for name in names:
        if not name.endswith(".json"):
            continue
        src = os.path.join(fdir, name)
        try:
            with open(src, "r") as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue  # mid-rewrite or corrupt: next sweep retries
        pid = int(snap.get("pid") or 0)
        if pids is not None and pid not in pids:
            continue
        if node_id is not None and snap.get("node_id") != node_id:
            continue
        if pids is None and node_id is None and require_dead \
                and _pid_alive(pid):
            continue
        snap["reason"] = reason
        snap["signal"] = snap.get("signal") or ""
        snap["bundled_at"] = time.time()
        snap["bundled_by"] = bundled_by
        dst = os.path.join(
            bundle_dir(session_dir),
            f"{snap.get('role', 'proc')}-{pid}-"
            f"{int(snap['bundled_at'] * 1000)}.json")
        try:
            _write_json_atomic(dst, snap)
            os.unlink(src)
        except OSError:
            continue
        snap["path"] = dst
        promoted.append(snap)
    return promoted


def read_bundles(session_dir: str) -> List[dict]:
    """All crash bundles in a session, oldest first. A corrupt or
    truncated bundle is skipped with a WARNING — a half-written file
    must never take the postmortem reader down with it."""
    bdir = bundle_dir(session_dir)
    try:
        names = sorted(os.listdir(bdir))
    except OSError:
        return []
    out = []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(bdir, name)
        try:
            with open(path, "r") as f:
                snap = json.load(f)
            if not isinstance(snap, dict) or "pid" not in snap:
                raise ValueError("not a bundle record")
        except (OSError, ValueError) as e:
            logger.warning("skipping corrupt crash bundle %s: %r",
                           path, e)
            continue
        snap["path"] = path
        out.append(snap)
    return out


def bundle_infos(session_dir: str) -> List[CrashBundleInfo]:
    """read_bundles() projected to the wire-registered summary rows."""
    out = []
    for snap in read_bundles(session_dir):
        out.append(CrashBundleInfo(
            role=str(snap.get("role", "")),
            pid=int(snap.get("pid") or 0),
            node_id=str(snap.get("node_id", "")),
            reason=str(snap.get("reason", "")),
            signal_name=str(snap.get("signal", "")),
            bundled_at=float(snap.get("bundled_at") or 0.0),
            written_at=float(snap.get("written_at") or 0.0),
            path=str(snap.get("path", "")),
            inflight=list(snap.get("inflight") or []),
        ))
    return out


def read_events_journal(session_dir: str,
                        severity: Optional[str] = None,
                        source: Optional[str] = None,
                        limit: int = 0,
                        offset: int = 0) -> List[dict]:
    """Parse the persisted event journal (works against a dead
    cluster). Malformed lines (torn writes) are dropped silently —
    the journal is append-only JSONL."""
    path = events_journal_path(session_dir)
    out = []
    try:
        with open(path, "r") as f:
            if offset:
                f.seek(offset)
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if severity and rec.get("severity") != severity:
                    continue
                if source and rec.get("source") != source:
                    continue
                out.append(rec)
    except OSError:
        return []
    if limit and len(out) > limit:
        out = out[-limit:]
    return out


def _write_json_atomic(path: str, obj: Any) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
