"""Runtime environments: per-task/actor env_vars, working_dir, py_modules
(ref: python/ray/_private/runtime_env/ — plugin.py, working_dir.py,
py_modules; the URI-cached packing model, minus conda/pip which require
network access).

Packing happens on the submitting driver: directories tar into the GCS
KV under a content hash (the reference's URI cache — identical dirs
upload once). Application happens in the executing worker: blobs extract
under the session dir, keyed by hash, and the process adopts the env
(env vars exported, working_dir becomes cwd + sys.path head, py_modules
prepended to sys.path).

Worker-granularity caveat (documented, reference-faithful in spirit):
the reference dedicates pool workers to one runtime env via lease
matching; here a shared pool worker adopts the env of the task it
executes, so mixing different runtime envs in one session works but
leaks env vars between tasks that share a worker.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import tarfile
from typing import Any, Dict, List, Optional

_KV_NS = "runtime_envs"
_ALLOWED = {"env_vars", "working_dir", "py_modules", "config", "pip", "uv"}


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for name in sorted(os.listdir(path)):
            if name in ("__pycache__",):
                continue
            tar.add(os.path.join(path, name), arcname=name)
    return buf.getvalue()


def prepare_runtime_env(core, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver side: validate, upload directory payloads, return the wire
    form stored on the TaskSpec."""
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _ALLOWED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys: {sorted(unknown)} "
            f"(supported: {sorted(_ALLOWED)})")
    wire: Dict[str, Any] = {}
    hasher = hashlib.sha256()
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in env_vars.items()):
            raise TypeError("env_vars must be Dict[str, str]")
        wire["env_vars"] = dict(env_vars)
        hasher.update(repr(sorted(env_vars.items())).encode())

    def upload(path: str) -> str:
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env path {path!r} is not a directory")
        blob = _pack_dir(path)
        key = hashlib.sha256(blob).hexdigest()
        if core.io.run(core.gcs.call(
                "kv_get", {"ns": _KV_NS, "key": key})) is None:
            core.io.run(core.gcs.call(
                "kv_put", {"ns": _KV_NS, "key": key, "value": blob}))
        hasher.update(key.encode())
        return key

    if runtime_env.get("working_dir"):
        hasher.update(b"working_dir:")  # field-tagged: {"working_dir": X}
        # and {"py_modules": [X]} must hash differently
        wire["working_dir_key"] = upload(runtime_env["working_dir"])
    for path in runtime_env.get("py_modules") or []:
        hasher.update(b"py_module:")
        wire.setdefault("py_module_keys", []).append(upload(path))
    for installer in ("pip", "uv"):
        reqs = runtime_env.get(installer)
        if not reqs:
            continue
        if isinstance(reqs, dict):  # {"packages": [...]} long form
            reqs = reqs.get("packages") or []
        if not isinstance(reqs, (list, tuple)) or not all(
                isinstance(r, str) for r in reqs):
            raise TypeError(f"{installer} must be a list of requirement "
                            "strings")
        wire[installer] = sorted(reqs)
        hasher.update(f"{installer}:{wire[installer]!r}".encode())
    if not wire:
        return None
    wire["hash"] = hasher.hexdigest()[:16]
    return wire


def _materialize_venv(requirements: List[str], installer: str) -> str:
    """Build (or reuse) a virtualenv holding the requirements; returns
    its site-packages path (ref: _private/runtime_env/{pip,uv}.py — the
    per-env venv with a URI cache keyed on the requirement set). The
    worker adopts it by sys.path prepend: pure-python deps resolve from
    the venv, everything else falls through to the base environment
    (``--system-site-packages``)."""
    import subprocess

    key = hashlib.sha256(
        f"{installer}:{requirements!r}:{sys.version_info[:2]}".encode()
    ).hexdigest()[:16]
    root = os.path.join("/tmp/ray_tpu_runtime_envs", f"venv_{key}")
    marker = os.path.join(root, ".ready")
    site = os.path.join(
        root, "lib", f"python{sys.version_info[0]}.{sys.version_info[1]}",
        "site-packages")
    if os.path.exists(marker):
        return site
    tmp = root + f".tmp.{os.getpid()}"
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    uv = shutil.which("uv") if installer == "uv" else None
    if uv:
        subprocess.run([uv, "venv", "--system-site-packages", tmp],
                       check=True, capture_output=True, timeout=300)
        install = [uv, "pip", "install", "--python",
                   os.path.join(tmp, "bin", "python")] + list(requirements)
    else:
        subprocess.run([sys.executable, "-m", "venv",
                        "--system-site-packages", tmp],
                       check=True, capture_output=True, timeout=300)
        # --no-build-isolation: sdists build against the venv's visible
        # setuptools (system-site) instead of pip fetching a build env
        # from an index — keeps air-gapped clusters working
        install = [os.path.join(tmp, "bin", "python"), "-m", "pip",
                   "install", "--no-input", "--no-build-isolation"] \
            + list(requirements)
    proc = subprocess.run(install, capture_output=True, timeout=1800)
    if proc.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"runtime_env {installer} install failed: "
            f"{proc.stderr.decode(errors='replace')[-2000:]}")
    open(os.path.join(tmp, ".ready"), "w").close()
    try:
        os.rename(tmp, root)  # atomic; concurrent builder loses cleanly
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
    return site


def apply_runtime_env(core, wire: Optional[dict],
                      applied: Dict[str, str]) -> None:
    """Worker side: adopt the env (idempotent per wire-hash; ``applied``
    is the executor's cache of already-materialized hashes)."""
    if not wire:
        return
    env_hash = wire.get("hash", "")
    if applied.get("hash") == env_hash:
        return

    def materialize(key: str) -> str:
        root = os.path.join("/tmp/ray_tpu_runtime_envs", key)
        marker = os.path.join(root, ".ready")
        if not os.path.exists(marker):
            blob = core.io.run(core.gcs.call(
                "kv_get", {"ns": _KV_NS, "key": key}))
            if blob is None:
                raise RuntimeError(f"runtime_env blob {key} missing from GCS")
            tmp = root + f".tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
                tar.extractall(tmp, filter="data")
            open(os.path.join(tmp, ".ready"), "w").close()
            try:
                os.rename(tmp, root)  # atomic; loser cleans up
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        return root

    for key, value in (wire.get("env_vars") or {}).items():
        os.environ[key] = value
    for installer in ("pip", "uv"):
        reqs = wire.get(installer)
        if reqs:
            site = _materialize_venv(reqs, installer)
            if site not in sys.path:
                sys.path.insert(0, site)
    for key in wire.get("py_module_keys") or []:
        path = materialize(key)
        if path not in sys.path:
            sys.path.insert(0, path)
    if wire.get("working_dir_key"):
        path = materialize(wire["working_dir_key"])
        if path not in sys.path:
            sys.path.insert(0, path)
        os.chdir(path)
    applied["hash"] = env_hash
