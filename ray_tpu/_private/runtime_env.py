"""Runtime environments: per-task/actor env_vars, working_dir, py_modules
(ref: python/ray/_private/runtime_env/ — plugin.py, working_dir.py,
py_modules; the URI-cached packing model, minus conda/pip which require
network access).

Packing happens on the submitting driver: directories tar into the GCS
KV under a content hash (the reference's URI cache — identical dirs
upload once). Application happens in the executing worker: blobs extract
under the session dir, keyed by hash, and the process adopts the env
(env vars exported, working_dir becomes cwd + sys.path head, py_modules
prepended to sys.path).

Worker-granularity caveat (documented, reference-faithful in spirit):
the reference dedicates pool workers to one runtime env via lease
matching; here a shared pool worker adopts the env of the task it
executes, so mixing different runtime envs in one session works but
leaks env vars between tasks that share a worker.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import tarfile
from typing import Any, Dict, List, Optional

_KV_NS = "runtime_envs"
_ALLOWED = {"env_vars", "working_dir", "py_modules", "config", "pip", "uv",
            "conda", "container"}


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for name in sorted(os.listdir(path)):
            if name in ("__pycache__",):
                continue
            tar.add(os.path.join(path, name), arcname=name)
    return buf.getvalue()


def prepare_runtime_env(core, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver side: validate, upload directory payloads, return the wire
    form stored on the TaskSpec."""
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _ALLOWED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys: {sorted(unknown)} "
            f"(supported: {sorted(_ALLOWED)})")
    wire: Dict[str, Any] = {}
    hasher = hashlib.sha256()
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in env_vars.items()):
            raise TypeError("env_vars must be Dict[str, str]")
        wire["env_vars"] = dict(env_vars)
        hasher.update(repr(sorted(env_vars.items())).encode())

    def upload(path: str) -> str:
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env path {path!r} is not a directory")
        blob = _pack_dir(path)
        key = hashlib.sha256(blob).hexdigest()
        if core.io.run(core.gcs.call(
                "kv_get", {"ns": _KV_NS, "key": key})) is None:
            core.io.run(core.gcs.call(
                "kv_put", {"ns": _KV_NS, "key": key, "value": blob}))
        hasher.update(key.encode())
        return key

    if runtime_env.get("working_dir"):
        hasher.update(b"working_dir:")  # field-tagged: {"working_dir": X}
        # and {"py_modules": [X]} must hash differently
        wire["working_dir_key"] = upload(runtime_env["working_dir"])
    for path in runtime_env.get("py_modules") or []:
        hasher.update(b"py_module:")
        wire.setdefault("py_module_keys", []).append(upload(path))
    for installer in ("pip", "uv"):
        reqs = runtime_env.get(installer)
        if not reqs:
            continue
        if isinstance(reqs, dict):  # {"packages": [...]} long form
            reqs = reqs.get("packages") or []
        if not isinstance(reqs, (list, tuple)) or not all(
                isinstance(r, str) for r in reqs):
            raise TypeError(f"{installer} must be a list of requirement "
                            "strings")
        wire[installer] = sorted(reqs)
        hasher.update(f"{installer}:{wire[installer]!r}".encode())
    if "conda" in runtime_env:
        # empty spec is a typo, not a no-op: validate-at-submission
        wire["conda"] = _canonical_conda_spec(runtime_env["conda"])
        hasher.update(f"conda:{wire['conda']!r}".encode())
    if "container" in runtime_env:
        container = runtime_env["container"]
        # capability-checked at SUBMISSION (ref: _private/runtime_env/
        # image_uri.py): a missing runtime is a driver-side error, not a
        # worker crash
        if not isinstance(container, dict) or "image" not in container:
            raise ValueError(
                'container runtime_env must be {"image": "..."} ')
        run_options = container.get("run_options") or []
        if not all(isinstance(o, str) for o in run_options):
            raise TypeError("container run_options must be strings")
        _container_runtime()  # raises if neither docker nor podman
        wire["container"] = {"image": container["image"],
                             "run_options": list(run_options)}
        if container.get("timeout_s"):
            wire["container"]["timeout_s"] = float(container["timeout_s"])
        hasher.update(f"container:{wire['container']!r}".encode())
    if not wire:
        return None
    wire["hash"] = hasher.hexdigest()[:16]
    return wire


def _canonical_conda_spec(conda) -> dict:
    """Normalize the conda field (ref: _private/runtime_env/conda.py):
    a dict environment spec, a path to an environment.yml, or the name
    of a pre-built env."""
    if not conda:
        raise ValueError("conda runtime_env must not be empty")
    if isinstance(conda, str):
        if conda.endswith((".yml", ".yaml")):
            import json as _json

            try:
                import yaml

                with open(conda) as f:
                    spec = yaml.safe_load(f)
            except ImportError:
                try:
                    with open(conda) as f:
                        spec = _json.loads(f.read())
                except ValueError:
                    raise RuntimeError(
                        f"parsing {conda!r} requires pyyaml (not "
                        "installed); JSON-formatted environment files "
                        "work without it") from None
            if not isinstance(spec, dict):
                raise TypeError(f"conda file {conda!r} must hold a mapping")
            return {"spec": spec}
        return {"name": conda}  # existing named env
    if isinstance(conda, dict):
        return {"spec": conda}
    raise TypeError("conda must be a spec dict, a .yml path, or an "
                    "env name")


def _atomic_materialize(root: str, build) -> str:
    """Build-once local cache: ``build(tmp_dir)`` populates a fresh
    directory that becomes ``root`` atomically; a concurrent builder
    loses the rename cleanly and adopts the winner's result. The
    ``.ready`` marker inside root is the completion witness (a crash
    mid-build leaves no marker, so the next caller rebuilds)."""
    import shutil

    marker = os.path.join(root, ".ready")
    if os.path.exists(marker):
        return root
    tmp = root + f".tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    try:
        build(tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    open(os.path.join(tmp, ".ready"), "w").close()
    try:
        os.rename(tmp, root)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
    return root


def _materialize_venv(requirements: List[str], installer: str) -> str:
    """Build (or reuse) a virtualenv holding the requirements; returns
    its site-packages path (ref: _private/runtime_env/{pip,uv}.py — the
    per-env venv with a URI cache keyed on the requirement set). The
    worker adopts it by sys.path prepend: pure-python deps resolve from
    the venv, everything else falls through to the base environment
    (``--system-site-packages``)."""
    import subprocess

    key = hashlib.sha256(
        f"{installer}:{requirements!r}:{sys.version_info[:2]}".encode()
    ).hexdigest()[:16]
    root = os.path.join("/tmp/ray_tpu_runtime_envs", f"venv_{key}")
    site = os.path.join(
        root, "lib", f"python{sys.version_info[0]}.{sys.version_info[1]}",
        "site-packages")
    def build(tmp):
        import shutil

        # venv must be created IN PLACE over the pre-made tmp dir
        shutil.rmtree(tmp, ignore_errors=True)
        uv = shutil.which("uv") if installer == "uv" else None
        if uv:
            subprocess.run([uv, "venv", "--system-site-packages", tmp],
                           check=True, capture_output=True, timeout=300)
            install = [uv, "pip", "install", "--python",
                       os.path.join(tmp, "bin", "python")] \
                + list(requirements)
        else:
            subprocess.run([sys.executable, "-m", "venv",
                            "--system-site-packages", tmp],
                           check=True, capture_output=True, timeout=300)
            # --no-build-isolation: sdists build against the venv's
            # visible setuptools (system-site) instead of pip fetching a
            # build env from an index — keeps air-gapped clusters working
            install = [os.path.join(tmp, "bin", "python"), "-m", "pip",
                       "install", "--no-input", "--no-build-isolation"] \
                + list(requirements)
        proc = subprocess.run(install, capture_output=True, timeout=1800)
        if proc.returncode != 0:
            raise RuntimeError(
                f"runtime_env {installer} install failed: "
                f"{proc.stderr.decode(errors='replace')[-2000:]}")

    _atomic_materialize(root, build)
    return site


def _container_runtime() -> str:
    """The node's container runtime, capability-checked (ref:
    _private/runtime_env/image_uri.py — podman-first there; docker-first
    here since that is what TPU-VM images ship)."""
    import shutil

    for name in ("docker", "podman"):
        path = shutil.which(name)
        if path:
            return path
    raise RuntimeError(
        "container runtime_env requires docker or podman on this "
        "node; neither is installed")


# The in-container entrypoint: plain pickle suffices to LOAD a
# cloudpickle blob as long as cloudpickle is importable in the image —
# the same contract the reference imposes (its images must contain ray).
_CONTAINER_BOOTSTRAP = """\
import pickle, sys
import cloudpickle
with open(sys.argv[1], "rb") as f:
    fn, args, kwargs = pickle.load(f)
out = fn(*args, **kwargs)
with open(sys.argv[2], "wb") as f:
    # cloudpickle BOTH ways: a result holding a by-value class (defined
    # in the driver's __main__, reconstructed here under a synthetic
    # module) round-trips only by value
    cloudpickle.dump(out, f, protocol=pickle.HIGHEST_PROTOCOL)
"""


def run_task_in_container(container: dict, fn, args, kwargs,
                          env_vars: Optional[dict] = None):
    """Execute one task body inside the image (ref: image_uri.py —
    there the whole worker process lives in the container; here the
    container is entered per task body, which keeps the pooled-worker
    model and its shm store host-side). The payload crosses via a
    bind-mounted scratch dir. A containerized body is a SEALED
    computation: the image needs python3 + cloudpickle, and the body
    cannot itself call .remote() (no control sockets are mounted)."""
    import pickle
    import shutil
    import subprocess
    import tempfile
    import uuid

    import cloudpickle

    exe = _container_runtime()
    timeout = float(container.get("timeout_s") or 1800.0)
    name = f"rtenv_{uuid.uuid4().hex[:12]}"
    scratch = tempfile.mkdtemp(prefix="rtenv_container_")
    payload = os.path.join(scratch, "in.pkl")
    result = os.path.join(scratch, "out.pkl")
    try:
        with open(payload, "wb") as f:
            cloudpickle.dump((fn, args, kwargs), f)
        # run as the worker's uid by default so the container's writes
        # into the bind-mounted scratch stay deletable by this process;
        # user run_options come later, so an explicit --user wins
        cmd = [exe, "run", "--rm", "--name", name,
               "--user", f"{os.getuid()}:{os.getgid()}",
               "-v", f"{scratch}:{scratch}"]
        for key, value in (env_vars or {}).items():
            cmd += ["-e", f"{key}={value}"]
        cmd += container.get("run_options") or []
        cmd += [container["image"], "python3", "-c",
                _CONTAINER_BOOTSTRAP, payload, result]
        try:
            proc = subprocess.run(cmd, capture_output=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired:
            # killing the CLI client does NOT stop the container; reap
            # it by name so it can't pin the node (and so --rm fires)
            subprocess.run([exe, "rm", "-f", name], capture_output=True,
                           timeout=60)
            raise RuntimeError(
                f"container task timed out after {timeout:.0f}s "
                f"(image {container['image']!r}); container reaped"
            ) from None  # the TimeoutExpired adds nothing to the message
        if proc.returncode != 0:
            raise RuntimeError(
                f"container task failed (image {container['image']!r}): "
                + proc.stderr.decode(errors="replace")[-2000:])
        with open(result, "rb") as f:
            return pickle.load(f)
    finally:
        shutil.rmtree(scratch, ignore_errors=True)
        if os.path.exists(scratch):
            # restrictive-mode leftovers (a container ignoring --user
            # can still create unreadable dirs): widen what we own,
            # per-entry so one EPERM doesn't abort the sweep, and retry
            for base, dirnames, filenames in os.walk(scratch):
                for entry in dirnames + filenames:
                    try:
                        os.chmod(os.path.join(base, entry), 0o700)
                    except OSError:
                        continue
            shutil.rmtree(scratch, ignore_errors=True)


def _conda_binary() -> str:
    """The node's conda-compatible solver, capability-checked (ref:
    _private/runtime_env/conda.py get_conda_activate_commands)."""
    import shutil

    for name in ("mamba", "micromamba", "conda"):
        path = shutil.which(name)
        if path:
            return path
    raise RuntimeError(
        "conda runtime_env requires conda/mamba/micromamba on this "
        "node; none is installed")


def _conda_site_packages(env_root: str) -> str:
    import glob

    hits = sorted(glob.glob(os.path.join(env_root, "lib", "python*",
                                         "site-packages")))
    if not hits:
        raise RuntimeError(
            f"conda env at {env_root} has no python site-packages")
    return hits[-1]


def _materialize_conda(canonical: dict) -> str:
    """Create (or reuse) the conda env; returns its site-packages.

    Adoption model matches the pip/uv path: the env's site-packages is
    prepended to sys.path of the (base-interpreter) worker — pure-python
    and ABI-compatible deps resolve from the env. (The reference swaps
    the whole worker interpreter; that needs per-lease worker exec and
    is stated, not hidden.) Cache key = canonical spec, so every worker
    on the node shares one materialized env per spec."""
    import json as _json
    import subprocess

    conda = _conda_binary()
    if "name" in canonical:
        # pre-built named env: resolve its prefix via the solver
        proc = subprocess.run([conda, "env", "list", "--json"],
                              capture_output=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                "conda env list failed: "
                + proc.stderr.decode(errors="replace")[-500:])
        try:
            envs = _json.loads(proc.stdout or b"{}").get("envs", [])
        except ValueError:
            raise RuntimeError(
                "conda env list produced non-JSON output: "
                + proc.stdout.decode(errors="replace")[:500]) from None
        for prefix in envs:
            if os.path.basename(prefix) == canonical["name"]:
                return _conda_site_packages(prefix)
        raise RuntimeError(
            f"conda env {canonical['name']!r} not found on this node")
    spec = canonical["spec"]
    key = hashlib.sha256(
        _json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
    root = os.path.join("/tmp/ray_tpu_runtime_envs", f"conda_{key}")

    def build(tmp):
        # the spec lives BESIDE the prefix: real conda refuses to create
        # into a non-empty directory
        spec_file = tmp + ".spec.json"
        with open(spec_file, "w") as f:
            _json.dump(spec, f)
        try:
            proc = subprocess.run(
                [conda, "env", "create", "-p", tmp, "-f", spec_file,
                 "--yes"],
                capture_output=True, timeout=1800)
        finally:
            try:
                os.unlink(spec_file)
            except OSError:
                pass
        if proc.returncode != 0:
            raise RuntimeError(
                "conda env create failed: "
                + proc.stderr.decode(errors="replace")[-2000:])

    _atomic_materialize(root, build)
    return _conda_site_packages(root)


def apply_runtime_env(core, wire: Optional[dict],
                      applied: Dict[str, str]) -> None:
    """Worker side: adopt the env (idempotent per wire-hash; ``applied``
    is the executor's cache of already-materialized hashes)."""
    if not wire:
        return
    env_hash = wire.get("hash", "")
    if applied.get("hash") == env_hash:
        return

    def materialize(key: str) -> str:
        def build(tmp):
            blob = core.io.run(core.gcs.call(
                "kv_get", {"ns": _KV_NS, "key": key}))
            if blob is None:
                raise RuntimeError(
                    f"runtime_env blob {key} missing from GCS")
            with tarfile.open(fileobj=io.BytesIO(blob)) as tar:
                tar.extractall(tmp, filter="data")

        return _atomic_materialize(
            os.path.join("/tmp/ray_tpu_runtime_envs", key), build)

    for key, value in (wire.get("env_vars") or {}).items():
        os.environ[key] = value
    for installer in ("pip", "uv"):
        reqs = wire.get(installer)
        if reqs:
            site = _materialize_venv(reqs, installer)
            if site not in sys.path:
                sys.path.insert(0, site)
    if wire.get("conda"):
        site = _materialize_conda(wire["conda"])
        if site not in sys.path:
            sys.path.insert(0, site)
    for key in wire.get("py_module_keys") or []:
        path = materialize(key)
        if path not in sys.path:
            sys.path.insert(0, path)
    if wire.get("working_dir_key"):
        path = materialize(wire["working_dir_key"])
        if path not in sys.path:
            sys.path.insert(0, path)
        os.chdir(path)
    applied["hash"] = env_hash
