"""Global flag table, env-var overridable.

TPU-native analog of the reference RAY_CONFIG system (ref:
src/ray/common/ray_config_def.h — 224 flags, each overridable via a RAY_<name>
env var and via the driver's _system_config). We keep the same contract:
 * every flag has a typed default,
 * `RAY_TPU_<NAME>` env vars override defaults at process start,
 * a driver-supplied dict overrides both and is propagated to workers through
   the control plane (workers call `apply_overrides` on connect).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"

# session roots live here (node session dirs, worker logs); single source
# of truth for every module that derives session paths
TEMP_ROOT = "/tmp/ray_tpu"


def session_log_dir(session_name: str) -> str:
    return os.path.join(TEMP_ROOT, session_name, "logs")


def _coerce(value: str, ty: type) -> Any:
    if ty is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if ty is dict or ty is list:
        return json.loads(value)
    return ty(value)


@dataclass
class Config:
    # --- scheduling ---
    scheduler_spread_threshold: float = 0.5   # hybrid policy: pack below, spread above
    lease_spill_min_queue_s: float = 0.5      # queued-lease settle time before spillback
    scheduler_top_k_fraction: float = 0.2     # top-k random choice among best nodes
    max_pending_lease_requests_per_scheduling_class: int = 10
    worker_lease_timeout_ms: int = 500
    # --- object store ---
    object_store_memory_bytes: int = 2 * 1024**3
    object_store_small_object_threshold: int = 100 * 1024  # inline below this
    object_spilling_enabled: bool = True      # evictees spill to disk
    object_spilling_dir: str = ""             # "" = TEMP_ROOT/spill/<store>
    object_spilling_threshold: float = 0.8
    object_store_eviction_fraction: float = 0.1
    # spill/restore I/O plane (object_store.py): chunked multi-worker
    # copies straight between spill files and the shm mapping (preadv/
    # sendfile, no intermediate bytes). Workers size the shared I/O
    # pool; restores additionally admit through a bytes-in-flight gate
    # that shares object_transfer_max_inflight_bytes with PullManager
    # so concurrent restores can't blow the store.
    object_spill_io_workers: int = 4
    object_spill_io_chunk_bytes: int = 8 * 1024**2
    # --- data shuffle (data/shuffle.py map/merge exchange) ---
    # partitions per exchange; 0 = auto (sort: max(input blocks,
    # total/fragment_target); random_shuffle: total/fragment_target,
    # layout-independent so a fixed seed is reproducible across block
    # layouts; groupby: fixed small default so maps pipeline)
    shuffle_num_partitions: int = 0
    # auto-partitioning aims each merged output block at this size
    shuffle_fragment_target_bytes: int = 16 * 1024**2
    # merge-task submission window (per-partition merges in flight)
    shuffle_merge_parallelism: int = 8
    # --- memory pressure (ref: memory_monitor.h:52 + killing policies) ---
    memory_monitor_refresh_ms: int = 500      # 0 disables the monitor
    memory_usage_threshold: float = 0.95      # host RSS fraction to act at
    memory_monitor_test_file: str = ""        # tests: file with a fraction
    max_grpc_message_bytes: int = 512 * 1024**2
    object_transfer_chunk_bytes: int = 8 * 1024**2
    # bulk transfer plane (object_transfer.py): parallel raw-frame
    # connections per pull, and the PullManager's bytes-in-flight budget
    object_transfer_streams: int = 4
    object_transfer_max_inflight_bytes: int = 512 * 1024**2
    # broadcast tree: a holder grants at most this many concurrent
    # senders-per-object; denied pullers re-poll the directory and
    # chain off freshly-completed copies instead of piling onto the one
    # origin (ref: push_manager.h:32 per-peer in-flight caps; BASELINE
    # envelope row: 1 GiB broadcast to 50+ nodes). Cost: one extra small
    # acquire RPC per cross-node pull (release is fire-and-forget);
    # latency-critical small-object workloads can set 0 to disable
    # gating entirely (no RPC is made then).
    object_transfer_max_senders_per_object: int = 2
    # --- fast lane (native shm task plane; ray_tpu/_private/fastlane.py) ---
    fastlane_width: int = 4                   # max lanes (leased workers)
    fastlane_window: int = 32                 # in-flight tasks per lane
    # max actors with an open fast lane per owner (each lane = 2 shm
    # rings + 2 threads); calls beyond the cap ride the asyncio path
    actor_lane_max: int = 64
    # --- workers ---
    num_workers_soft_limit: int = -1          # -1: num_cpus
    worker_startup_timeout_s: float = 60.0
    # forkserver worker factory (worker_factory.py): pay worker imports
    # once per node, fork per worker. Off = cold Popen per worker.
    worker_factory_enabled: bool = True
    # max workers mid-startup at once (fork-storm guard for envelope-
    # depth actor counts; dedicated spawns queue behind the burst)
    worker_spawn_burst: int = 16
    # dialing an already-registered worker (its RPC server is live): short
    worker_dial_timeout_s: float = 3.0
    worker_register_timeout_s: float = 30.0
    idle_worker_killing_time_threshold_ms: int = 800
    prestart_workers: bool = True
    # --- fault tolerance ---
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0
    health_check_period_ms: int = 1000
    health_check_failure_threshold: int = 5
    # per-probe RPC timeout for the GCS's ACTIVE node health checks
    # (ref: gcs_health_check_manager.h kDefaultTimeoutMs); 0 disables
    # active probing (disconnect-only death detection)
    health_check_timeout_ms: int = 2000
    # resource view propagation (syncer.py): "hub" = GCS pubsub fan-out
    # (O(N^2) msgs/interval through one loop), "gossip" = push-pull
    # anti-entropy, O(fanout) per node, O(log N) rounds to converge
    # (ref: ray_syncer.h:83)
    resource_sync_mode: str = "hub"
    resource_sync_interval_s: float = 1.0
    resource_sync_fanout: int = 2
    lineage_pinning_enabled: bool = True
    max_lineage_bytes: int = 1024**3
    # --- chaos / testing (mirrors rpc_chaos.h fault injection) ---
    testing_rpc_failure: str = ""             # "method=prob_req:prob_resp,..."
    # failpoint harness (_private/failpoints.py): named fault-injection
    # sites at the hazard boundaries the graftlint error-plane passes
    # audit. "site=action[:arg][:max_hits],..." — actions raise/delay/
    # drop; "site@detail=..." scopes to one RPC method. Empty = every
    # site is a single dict lookup (inert).
    failpoints: str = ""
    # graftlint runtime lock-order witness (devtools/graftlint/witness):
    # control-plane locks built through _private/locking.py become
    # instrumented WitnessLocks feeding a global lockdep-style order
    # graph that raises on cycle formation. Debug/CI-stress only —
    # read at lock CONSTRUCTION, so flip it before init().
    lock_witness_enabled: bool = False
    # locality-aware leasing: lease at the node holding a task's argument
    # bytes when the known dependency mass there reaches this many bytes
    # (ref: lease_policy.h LocalityAwareLeasePolicy). 0 disables.
    scheduler_locality_min_bytes: int = 64 * 1024
    # per-try timeout for lease RPCs; 0 = wait forever (reliable transport).
    # Chaos/unreliable setups set this so dropped frames trigger a retry,
    # which the raylet dedups by request id.
    lease_rpc_timeout_s: float = 0.0
    # bound on the GCS's outbound control RPCs to raylets (placement-
    # group reserve/commit/cancel fan-out): a dead or wedged raylet
    # surfaces as GcsTimeoutError instead of hanging the scheduling
    # loop. 0 = wait forever.
    gcs_rpc_timeout_s: float = 30.0
    # --- stall sentinel (hang/straggler detection) ---
    # raylet task watchdog period; 0 disables the watchdog. Each tick the
    # raylet compares every RUNNING task's age against an adaptive
    # per-scheduling-class threshold (EMA of completed durations times
    # task_stall_ema_factor, floored at task_stall_threshold_s), captures
    # the implicated worker's stack via its dump_stacks RPC, and emits a
    # WARNING cluster event with the stack attached.
    task_watchdog_interval_s: float = 5.0
    # floor for the adaptive RUNNING-too-long threshold; a class with no
    # completion history yet stalls only past this floor
    task_stall_threshold_s: float = 60.0
    # a task is suspect once it runs this multiple of its class's EMA
    task_stall_ema_factor: float = 10.0
    # GCS collective watchdog period; 0 disables. A collective step with
    # some-but-not-all participant arrivals older than
    # collective_stall_timeout_s emits a "hung collective" event naming
    # the missing ranks/hosts and pulls their stacks.
    collective_watchdog_interval_s: float = 2.0
    collective_stall_timeout_s: float = 30.0
    # transfer stall detector: a pull whose contiguous byte watermark has
    # not advanced for this long is flagged (0 disables); checked by the
    # raylet watchdog tick against the store's in-progress registry.
    transfer_stall_timeout_s: float = 30.0
    # --- tail tolerance (hedged execution + straggler-aware scheduling,
    #     ref: The Tail at Scale — the mitigation half of the stall
    #     sentinel's detection plane) ---
    # speculative re-execution of idempotent tasks: when a RUNNING task
    # outlives its hedge delay (per-fn EMA of past push->reply durations
    # times task_hedge_ema_factor, floored at task_hedge_min_delay_s) or
    # the raylet watchdog flags it and hints the owner, the owner pushes
    # a second copy of the same TaskSpec to a different node; the first
    # reply wins and is published exactly once, the loser is cancelled.
    # Only tasks declared @remote(idempotent=True) (or
    # speculation="auto") are eligible.
    task_speculation_enabled: bool = False
    task_hedge_ema_factor: float = 3.0
    task_hedge_min_delay_s: float = 1.0
    # serve request hedging (serve/handle.py): once a handle has
    # serve_hedge_min_samples latency samples, a request still pending
    # past that sample set's serve_hedge_quantile latency is hedged to
    # the second-choice replica (first response wins, loser's reply is
    # discarded), provided hedges stay under serve_hedge_budget of total
    # requests. 0.0 disables hedging entirely (default: zero overhead).
    serve_hedge_quantile: float = 0.0
    serve_hedge_budget: float = 0.05
    serve_hedge_min_samples: int = 16
    # --- fleet KV plane (serve/kv_router.py): prefix-cache-aware
    #     routing + disaggregated prefill/decode serving ---
    # route requests to the replica holding the longest cached prompt
    # prefix (replicas publish truncated prefix-page digests through the
    # controller's reconcile tick); off = pure pow-2 load routing
    serve_prefix_routing_enabled: bool = True
    # how often the controller re-polls replica prefix summaries AND how
    # often handles re-pull the aggregated table; a summary older than
    # 3x this is stale and the handle falls back to load routing
    serve_prefix_summary_interval_s: float = 2.0
    # spill threshold: a prefix-match winner with more than this many
    # of the handle's own in-flight requests loses to pow-2 (cache
    # affinity must not defeat load balancing under a hot prefix)
    serve_prefix_spill_queue_depth: int = 8
    # prefill->decode KV handoff: exported page payloads are split into
    # object-store puts of at most this many bytes so one long prompt's
    # KV doesn't serialize as a single giant object
    serve_kv_handoff_chunk_bytes: int = 8 * 1024**2
    # speculative decoding, fleet verify mode: decode-pool replicas
    # corroborate their local draft verification against the prefill
    # pool (which batch-verifies on otherwise-idle decode-phase chips).
    # Off by default — the local verify is always authoritative; fleet
    # verify adds cross-pool agreement counters and warms the path for
    # drafter-on-decode / verifier-on-prefill placements.
    llm_spec_fleet_verify: bool = False
    llm_spec_fleet_verify_timeout_s: float = 2.0
    # straggler-aware scheduling: the raylet refreshes per-node straggler
    # scores (GCS lateness EMA relative to cluster mean) on its watchdog
    # tick and deprioritizes nodes scoring >= this threshold in spread /
    # hybrid placement whenever a non-straggler alternative is feasible.
    # 0 disables score-based deprioritization (avoid_nodes still works).
    straggler_deprioritize_threshold: float = 3.0
    # drain-and-restart: when the watchdog flags a non-actor task wedged
    # past straggler_drain_after_factor x its stall threshold, the raylet
    # kills the worker so the owner's retry path resubmits elsewhere —
    # rescuing gang collectives before CollectiveTimeoutError. Off by
    # default: it trades a duplicate execution for tail latency.
    straggler_drain_enabled: bool = False
    straggler_drain_after_factor: float = 2.0
    # --- profiling & memory attribution plane (util/stacks.py,
    #     util/hbm.py, state.memory_report; ref: Google-Wide Profiling —
    #     always-on sampling at <1% overhead) ---
    # always-on per-worker sampling profiler rate (folded wall/CPU
    # stacks, drained by `cli profile` / the GCS merge). 0 disables the
    # ambient sampler entirely; on-demand bursts still work at any rate.
    profiling_sample_hz: float = 0.0
    # frames kept per sampled stack (deeper frames are truncated)
    profiling_max_stack_depth: int = 64
    # submit-path stage timers (core_worker.submit_task histograms, the
    # ROADMAP item-2 baseline instrument). Off = zero perf_counter reads
    # on the submit hot path.
    submit_stage_timers_enabled: bool = True
    # start tracemalloc in every worker so memory_report can attribute
    # per-worker Python heap deltas (tracemalloc costs ~2x allocation
    # overhead — opt-in)
    tracemalloc_enabled: bool = False
    # HBM gauge publication period (per-chip live-buffer/fragmentation
    # gauges read from the jax backend, piggybacked on the stall-probe
    # tick). 0 disables.
    hbm_gauge_interval_s: float = 10.0
    # memory_report flags a pinned, ownerless object older than this as
    # a leak suspect
    memory_leak_age_s: float = 60.0
    # --- logging / metrics ---
    event_log_enabled: bool = True
    metrics_report_interval_ms: int = 2000
    # --- SLO observability plane (ray_tpu/slo.py; GCS-side series
    #     retention + burn-rate monitor) ---
    # keep per-series ring buffers of the aggregated metrics table,
    # sampled on the GCS evaluation tick (the in-memory-TSDB layer the
    # SLO monitor and dashboard sparklines read). Off = last-value-only
    # metrics table, SLO engine inert.
    metrics_series_enabled: bool = True
    # ring length per series; retention ~= max_samples x min_interval
    metrics_series_max_samples: int = 256
    # downsampling floor: appends closer together than this are dropped
    metrics_series_min_interval_s: float = 2.0
    # total series bound, FIFO-evicted (tenant tags multiply cardinality)
    metrics_series_max_series: int = 4000
    # GCS sampling + SLO evaluation tick; 0 disables the loop entirely
    slo_eval_interval_s: float = 2.0
    # declarative SLO specs, each "name: indicator op value [@ k=v,...]
    # [window=60s]" — e.g. "chat-ttft: ttft_p99 < 250ms @ tenant=acme",
    # "chat-avail: availability >= 99.9% @ deployment=Chat". Also
    # settable at runtime via state.set_slo_specs / the loadgen.
    slo_specs: list = field(default_factory=list)
    # multi-window burn-rate alerting (SRE Workbook ch.5): an alert
    # fires when the error-budget burn rate exceeds the threshold over
    # BOTH windows of a pair ("short,long" seconds). Fast pair emits
    # ERROR events, slow pair WARNING. Defaults are the Workbook's
    # 5m/1h + 30m/6h shape scaled to this cluster's 2 s ticks.
    slo_fast_burn_windows_s: str = "30,300"
    slo_fast_burn_threshold: float = 14.4
    slo_slow_burn_windows_s: str = "120,600"
    slo_slow_burn_threshold: float = 6.0
    # tenant id assumed for requests arriving without an X-Tenant-ID
    # header (per-tenant accounting; serve/proxy.py)
    serve_default_tenant: str = "default"
    # --- black-box plane (_private/blackbox.py: flight rings, crash
    #     bundles, durable observability state; read by cli postmortem) ---
    # per-process flight recorder: bounded ring of recent events/logs/
    # stacks/in-flight ids, flushed to a session-dir flight file and
    # promoted to a crash bundle on abnormal exit or survivor sweep.
    blackbox_enabled: bool = True
    # ring length (events and log records each keep this many entries)
    blackbox_ring_size: int = 256
    # flight-file rewrite period; bounds how stale a SIGKILL'd corpse's
    # bundle can be. Appends are off the submit hot path either way.
    blackbox_flush_interval_s: float = 2.0
    # GCS durable-observability checkpoint period (SeriesStore rings,
    # SLO monitor state, aggregated metrics table, task-event table →
    # gcs_storage). 0 disables checkpointing; restore still runs if a
    # prior checkpoint exists in the journal.
    obs_checkpoint_interval_s: float = 10.0
    # persist cluster events as JSONL next to the bundles so
    # `cli events --follow` works against a dead cluster
    event_journal_enabled: bool = True
    # after restoring SLO state on head restart, suppress NEW burn-rate
    # alert transitions for this long — the restart gap must not page
    slo_restore_grace_s: float = 30.0
    # raylet clock-sync period against the GCS clock (NTP-style offset
    # piggybacked on ping; raylet.py _clock_sync_loop). 0 disables —
    # timelines then merge raw per-node wall clocks.
    clock_sync_interval_s: float = 30.0
    # --- training goodput plane (train/telemetry.py; GCS-side ledger
    #     in _private/gcs.py handle_train_report) ---
    # per-step phase telemetry: timeline in train/session.py, compile/
    # compute attribution in train/step.py. Off = bare jitted step
    # (no per-call device sync), no TrainStepTelemetry records.
    train_telemetry_enabled: bool = True
    # first-call-per-shape faster than this with no new persistent-cache
    # entries classifies as a cache hit rather than a cold compile
    train_compile_cache_hit_threshold_s: float = 0.5
    # accelerator peak (bf16 matmul) flops per chip for MFU math —
    # 0 leaves MFU unreported (v5p ~459e12, v5e ~197e12)
    train_peak_flops_per_chip: float = 0.0
    # --- device plane ---
    # Serving decode attention: stream KV pages through the Pallas
    # paged-attention kernel (ops/paged_attention.py) instead of the
    # XLA jnp.take gather. Measured r3 on 1x v5e (llama-400m, B=16,
    # burst=32, ~300-token contexts): kernel ~400 tok/s vs gather
    # ~1050-1130 tok/s, with both a scanned and an UNROLLED layer loop —
    # at short contexts (~5 pages/seq) the kernel's per-page sequential
    # DMAs and skinny [rep, page] matmuls lose to one big fused gather
    # einsum. Re-measured r3 on 1x v5e across ctx 512..8192 (B=4,
    # burst=32): the gather path wins at EVERY length — our kernel is
    # 0.69x..0.18x of gather, and even jax's production
    # pallas.ops.tpu.paged_attention (multi-page compute blocks,
    # pipelined DMA) is 0.8x of gather at ctx=8192 (5.6 vs 6.9 ms per
    # 24-layer step). The burst design gathers ONCE per 32-step burst,
    # so per-step attention reads a contiguous layout at streaming
    # bandwidth; paged kernels only pay off when the gather copy itself
    # is unaffordable (HBM headroom), not for speed at these shapes.
    llm_paged_kernel: bool = False
    # Auto-select: when llm_paged_kernel is off, a decode round whose
    # bucketed block-table span is >= this many pages uses the Pallas
    # kernel anyway (0 disables auto-select). The span is a static shape
    # (engine buckets it), so each (span, path) pair is its own compiled
    # executable — flipping per round costs nothing at steady state.
    # Re-measured r4 at TRUE 8k occupancy (400m, B=4, ctx=7650, 120/120
    # pages resident, v5e): gather 486 tok/s vs paged kernel 127 tok/s
    # — the burst design's once-per-32-steps contiguous gather beats
    # per-step paged DMA at every feasible occupancy on this chip, so
    # auto-select stays disabled BY MEASUREMENT, not by default.
    llm_paged_kernel_min_ctx_pages: int = 0
    # bind host for the per-process PJRT transfer server backing
    # DeviceChannel (experimental/device_channel.py); must be routable
    # from peer hosts — "" = loopback (single host). TPU pods set the
    # node's DCN-reachable IP.
    device_transfer_host: str = ""
    mesh_compile_cache_dir: str = ""
    default_device_platform: str = ""         # "" = jax default
    ici_mesh_auto_axis_order: bool = True

    def apply_overrides(self, overrides: Dict[str, Any]) -> None:
        valid = {f.name: f.type for f in fields(self)}
        for key, value in overrides.items():
            if key not in valid:
                raise ValueError(f"Unknown config flag: {key}")
            setattr(self, key, value)

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in fields(cls):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                ty = type(getattr(cfg, f.name))
                setattr(cfg, f.name, _coerce(os.environ[env_key], ty))
        return cfg

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def reset_global_config() -> None:
    global _global_config
    _global_config = None
