"""Gossip resource syncer: peer-to-peer eventual consistency.

TPU-native analog of the reference resource syncer (ref:
src/ray/common/ray_syncer/ray_syncer.h:83 — bidirectional streaming of
versioned resource views with eventual consistency). The default
hub-and-spoke path (raylet -> GCS report -> pubsub fan-out) makes every
availability change O(nodes) pushes through ONE asyncio loop — O(N²)
messages per interval cluster-wide, all on the head. Gossip mode
replaces the fan-out: each raylet keeps a versioned view
{node: (seq, available, pending)} and runs push-pull anti-entropy
rounds with `fanout` random peers; information spreads in O(log N)
rounds while per-node load stays O(fanout) regardless of cluster size.
The GCS still receives each node's own reports (observability,
autoscaler) — it just stops being the broadcast hub.

Protocol (digest-driven deltas; the reference streams deltas, not
snapshots — ray_syncer.h streaming protocol):

    -> "syncer_sync" {"from": hex, "digest": {node_hex: seq}}
    <- {"entries": {...},   # what the caller lacks per its digest
        "want": [hex...]}   # what the CALLEE lacks per that digest
    -> "syncer_push" {"from": hex, "entries": {...}}  # only if want≠[]

Both directions ship EXACTLY the entries the other side proved it
needs, so a steady-state round is one digest-sized RPC with zero
entries — O(changes) bytes, not O(N) (the r4 protocol shipped the full
view every round). The digest itself stays O(N) but is ~40 bytes/node;
it is the anti-entropy backbone and the price of exactness.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Dict, Optional

__all__ = ["ResourceSyncer"]


class ResourceSyncer:
    def __init__(self, raylet, interval_s: float = 1.0, fanout: int = 2):
        self.raylet = raylet
        self.interval_s = interval_s
        self.fanout = fanout
        # node_hex -> {"seq", "available"}
        self.view: Dict[str, Dict[str, Any]] = {}
        self._task: Optional[asyncio.Task] = None
        self._tombstones: Dict[str, float] = {}   # node_hex -> expiry
        self.rounds = 0
        # delta-efficiency observability (scale tests assert on these)
        self.entries_pushed = 0
        self.entries_received = 0

    # ------------------------------------------------------------ local
    def local_update(self, available: dict, pending: list,
                     seq: int) -> None:
        # entries carry ONLY what consumers read (seq ordering +
        # availability): every extra field ships O(N * fanout) copies
        # per interval cluster-wide
        self.view[self.raylet.node_id.hex()] = {
            "seq": seq, "available": available,
        }

    # how long an evicted node stays tombstoned: long enough for every
    # peer to hear the (hub-authoritative) death, short enough that the
    # set shrinks under sustained churn
    _TOMBSTONE_TTL_S = 60.0

    def evict(self, node_hex: str) -> None:
        """Drop a node from the gossip view (death/removal is
        hub-authoritative; without eviction dead entries gossip
        forever and the view grows with churn). A TTL'd tombstone
        stops a laggard peer that hasn't heard the death yet from
        gossiping the entry straight back in."""
        self.view.pop(node_hex, None)
        self._tombstones[node_hex] = time.monotonic() + self._TOMBSTONE_TTL_S

    def _tombstoned(self, node_hex: str) -> bool:
        exp = self._tombstones.get(node_hex)
        if exp is None:
            return False
        if time.monotonic() > exp:
            del self._tombstones[node_hex]
            return False
        return True

    def digest(self) -> Dict[str, int]:
        return {node: entry["seq"] for node, entry in self.view.items()}

    def entries_newer_than(self, digest: Dict[str, int]) -> Dict[str, dict]:
        return {node: entry for node, entry in self.view.items()
                if entry["seq"] > digest.get(node, -1)}

    def apply(self, entries: Dict[str, dict]) -> int:
        """Merge peer entries (last-writer-wins by seq). Returns how
        many were news. Freshly learned availability feeds the same
        spillback view the hub pushes maintained."""
        applied = 0
        my_hex = self.raylet.node_id.hex()
        # hub-authoritative membership: node death outlives any TTL, so
        # entries for nodes the hub declared dead are dropped (and
        # re-tombstoned) no matter how late the laggard peer gossips
        dead = getattr(self.raylet, "_dead_node_hexes", None) or ()
        for node, entry in entries.items():
            if node == my_hex:
                continue  # own state is authoritative locally
            if node in dead:
                # the TTL may have expired: refresh it so OUR next
                # rounds don't relay the zombie onward either
                self.evict(node)
                continue
            if self._tombstoned(node):
                # a laggard peer must not resurrect it — and its
                # staleness proves the death hasn't reached everyone
                # yet, so restart the TTL clock
                self._tombstones[node] = (time.monotonic()
                                          + self._TOMBSTONE_TTL_S)
                continue
            cur = self.view.get(node)
            if cur is not None and cur["seq"] >= entry["seq"]:
                continue
            self.view[node] = entry
            applied += 1
            self.raylet._apply_peer_resources(node, entry["available"])
        return applied

    # ----------------------------------------------------------- gossip
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.interval_s)
                await self._round()
            except asyncio.CancelledError:
                raise  # stop() cancelled us: keep the task CANCELLED
            except Exception:
                continue  # a bad peer/round must not stop anti-entropy

    async def _round(self) -> None:
        peers = [(nid, addr) for nid, (addr, _)
                 in self.raylet._remote_nodes.items()]
        if not peers:
            return
        random.shuffle(peers)
        my_hex = self.raylet.node_id.hex()
        for node_id, address in peers[: self.fanout]:
            try:
                client = await self.raylet._peer_client(address)
                reply = await client.call("syncer_sync", {
                    "from": my_hex,
                    "digest": self.digest(),
                }, timeout=5.0)
            except Exception:
                continue
            if not reply:
                continue
            got = reply.get("entries", {})
            self.entries_received += len(got)
            self.apply(got)
            want = reply.get("want", ())
            push = {n: self.view[n] for n in want
                    if n in self.view and not self._tombstoned(n)}
            if push:
                self.entries_pushed += len(push)
                try:
                    await client.call("syncer_push", {
                        "from": my_hex, "entries": push}, timeout=5.0)
                except Exception:
                    continue
        self.rounds += 1

    # ------------------------------------------------------------ server
    async def handle_sync(self, payload: dict) -> dict:
        """Digest exchange: answer with what the caller lacks, and name
        what WE lack per its digest (it follows up with syncer_push)."""
        digest = payload.get("digest", {})
        answer = self.entries_newer_than(digest)
        self.entries_pushed += len(answer)
        want = [node for node, seq in digest.items()
                if seq > self._seq_of(node) and not self._tombstoned(node)]
        return {"entries": answer, "want": want}

    def _seq_of(self, node_hex: str) -> int:
        entry = self.view.get(node_hex)
        return -1 if entry is None else entry["seq"]

    async def handle_push(self, payload: dict) -> int:
        """Second half of a round: the entries we told the caller we
        want."""
        got = payload.get("entries", {})
        self.entries_received += len(got)
        return self.apply(got)
