"""Gossip resource syncer: peer-to-peer eventual consistency.

TPU-native analog of the reference resource syncer (ref:
src/ray/common/ray_syncer/ray_syncer.h:83 — bidirectional streaming of
versioned resource views with eventual consistency). The default
hub-and-spoke path (raylet -> GCS report -> pubsub fan-out) makes every
availability change O(nodes) pushes through ONE asyncio loop — O(N²)
messages per interval cluster-wide, all on the head. Gossip mode
replaces the fan-out: each raylet keeps a versioned view
{node: (seq, available, pending)} and runs push-pull anti-entropy
rounds with `fanout` random peers; information spreads in O(log N)
rounds while per-node load stays O(fanout) regardless of cluster size.
The GCS still receives each node's own reports (observability,
autoscaler) — it just stops being the broadcast hub.

Protocol (one raylet->raylet RPC per round, "syncer_sync"):
    -> {"from": hex, "digest": {node_hex: seq}, "entries": {...}}
    <- {"entries": {node_hex: entry}}   # what the caller was missing
The request carries entries the CALLER believes the callee lacks (push),
the reply returns what the CALLEE has newer (pull).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Dict, Optional

__all__ = ["ResourceSyncer"]


class ResourceSyncer:
    def __init__(self, raylet, interval_s: float = 1.0, fanout: int = 2):
        self.raylet = raylet
        self.interval_s = interval_s
        self.fanout = fanout
        # node_hex -> {"seq", "available"}
        self.view: Dict[str, Dict[str, Any]] = {}
        self._task: Optional[asyncio.Task] = None
        self.rounds = 0

    # ------------------------------------------------------------ local
    def local_update(self, available: dict, pending: list,
                     seq: int) -> None:
        # entries carry ONLY what consumers read (seq ordering +
        # availability): every extra field ships O(N * fanout) copies
        # per interval cluster-wide
        self.view[self.raylet.node_id.hex()] = {
            "seq": seq, "available": available,
        }

    def evict(self, node_hex: str) -> None:
        """Drop a node from the gossip view (death/removal is
        hub-authoritative; without eviction dead entries gossip
        forever and the view grows with churn)."""
        self.view.pop(node_hex, None)

    def digest(self) -> Dict[str, int]:
        return {node: entry["seq"] for node, entry in self.view.items()}

    def entries_newer_than(self, digest: Dict[str, int]) -> Dict[str, dict]:
        return {node: entry for node, entry in self.view.items()
                if entry["seq"] > digest.get(node, -1)}

    def apply(self, entries: Dict[str, dict]) -> int:
        """Merge peer entries (last-writer-wins by seq). Returns how
        many were news. Freshly learned availability feeds the same
        spillback view the hub pushes maintained."""
        applied = 0
        my_hex = self.raylet.node_id.hex()
        for node, entry in entries.items():
            if node == my_hex:
                continue  # own state is authoritative locally
            cur = self.view.get(node)
            if cur is not None and cur["seq"] >= entry["seq"]:
                continue
            self.view[node] = entry
            applied += 1
            self.raylet._apply_peer_resources(node, entry["available"])
        return applied

    # ----------------------------------------------------------- gossip
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.interval_s)
                await self._round()
            except asyncio.CancelledError:
                return
            except Exception:
                continue  # a bad peer/round must not stop anti-entropy

    async def _round(self) -> None:
        peers = [(nid, addr) for nid, (addr, _)
                 in self.raylet._remote_nodes.items()]
        if not peers:
            return
        random.shuffle(peers)
        for node_id, address in peers[: self.fanout]:
            try:
                client = await self.raylet._peer_client(address)
                # push-pull: the request carries our WHOLE view (N
                # entries of ~100 bytes — the peer's seqs dedupe on
                # apply), the reply returns only what we lack per our
                # digest. Per-peer delta tracking would trim the push
                # half; the reply half is already delta-sized.
                reply = await client.call("syncer_sync", {
                    "from": self.raylet.node_id.hex(),
                    "digest": self.digest(),
                    "entries": self.view,
                }, timeout=5.0)
                if reply:
                    self.apply(reply.get("entries", {}))
            except Exception:
                continue
        self.rounds += 1

    # ------------------------------------------------------------ server
    async def handle_sync(self, payload: dict) -> dict:
        """Peer round: absorb its entries, answer with what it lacks."""
        self.apply(payload.get("entries", {}))
        return {"entries": self.entries_newer_than(
            payload.get("digest", {}))}
