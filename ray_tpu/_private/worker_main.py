"""Worker process: registers with its raylet, executes pushed tasks.

TPU-native analog of the reference worker runtime (ref: src/ray/core_worker/
core_worker_process.cc:98 RunTaskExecutionLoop, transport/task_receiver.h,
actor_scheduling_queue.h; python/ray/_private/workers/default_worker.py).

Execution model: the process's RpcServer accepts `push_task` directly from
submitting core workers (no raylet hop on the hot path). Normal tasks run on a
small thread pool; an actor promotes the worker to a dedicated actor runtime —
a single ordered execution thread fed FIFO (per-caller order is preserved by
the connection stream), with `max_concurrency > 1` widening the pool.

Every return value is sealed into the shared object store (so any process can
resolve it via the raylet directory) and small values are additionally inlined
in the reply as the owner's fast path.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import queue
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import cloudpickle

from .config import global_config
from . import failpoints
from . import locking
from .core_worker import CoreWorker
from .ids import JobID, NodeID, ObjectID, WorkerID
from .object_store import SharedObjectStore
from .rpc import RpcClient, RpcServer
from . import serialization as ser
from .task_spec import ArgKind, TaskSpec
from .. import exceptions as exc
from ..util import stacks


def _cheap_size_bound(value, limit: int, _depth: int = 2) -> bool:
    """Heuristic (not a proof): True when ``value`` looks small enough
    to serialize on the actor's event loop without stalling it. Arrays
    expose nbytes, strings/bytes their length; narrow containers are
    inspected two levels deep (so [big_array, big_array] offloads).
    Opaque custom objects pass — they serialize on the loop, matching
    the reference's async actors (whose returns also serialize on the
    loop thread that ran the task)."""
    nb = getattr(value, "nbytes", None)
    # int check matters: objects with dynamic __getattr__ (actor
    # handles) synthesize a non-numeric .nbytes
    if isinstance(nb, int):
        return nb <= limit
    if isinstance(value, (bytes, bytearray, str)):
        return len(value) <= limit
    if isinstance(value, (list, tuple, set, frozenset, dict)):
        if len(value) > 256:
            return False  # wide containers: size unknowable cheaply
        if _depth <= 0:
            return True
        items = value.values() if isinstance(value, dict) else value
        return all(_cheap_size_bound(v, limit, _depth - 1)
                   for v in items)
    return True


def _maybe_span(spec: TaskSpec):
    """Execution span when the spec carries a trace context (tracing
    enabled at the driver); a no-op context otherwise."""
    import contextlib

    ctx = getattr(spec, "trace_ctx", None)
    if ctx is None:
        return contextlib.nullcontext()
    from ..util.tracing import task_span

    return task_span(ctx, spec.function.repr_name)


def _resolve_actor_method(instance, name: str):
    """Bound method lookup with a fallback for the injected dynamic-call
    entry point: classes pickled BY REFERENCE re-import without the
    driver-side ActorClass injection, so the compiled-DAG loop method
    must resolve from ray_tpu.actor here."""
    try:
        return getattr(instance, name)
    except AttributeError:
        if name == "_rtpu_dyn_call":
            from ..actor import _rtpu_dyn_call

            return lambda *a, **k: _rtpu_dyn_call(instance, *a, **k)
        raise


class _GenBudget:
    """Producer-side backpressure (ref: generator_waiter.h): the generator
    thread blocks while produced - consumed >= threshold."""

    def __init__(self, threshold: int):
        self.threshold = threshold
        self.consumed = 0
        self._cond = locking.make_condition("_GenBudget._cond")

    def ack(self, consumed: int) -> None:
        with self._cond:
            self.consumed = max(self.consumed, consumed)
            self._cond.notify_all()

    def wait_for_budget(self, produced: int) -> None:
        if self.threshold <= 0:
            return
        with self._cond:
            while produced - self.consumed >= self.threshold:
                self._cond.wait(timeout=1.0)


class SealBatcher:
    """Coalesces seal notifications into one ``objects_sealed_batch``
    RPC per flush window. Per-return round trips to the raylet dominate
    trivial-task latency otherwise (ref: task_event_buffer.h applies the
    same batching idea to task events)."""

    def __init__(self, core: CoreWorker, raylet: RpcClient,
                 window_s: float = 0.002):
        self.core = core
        self.raylet = raylet
        self.window_s = window_s
        self._q: List[Tuple[ObjectID, int]] = []
        self._lock = locking.make_lock("SealBatcher._lock")
        self._event = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="seal_batcher")
        self._thread.start()

    def add(self, oid: ObjectID, size: int) -> None:
        with self._lock:
            self._q.append((oid, size))
        self._event.set()

    def _loop(self) -> None:
        import time as _time

        while True:
            self._event.wait()
            _time.sleep(self.window_s)  # coalesce a burst
            with self._lock:
                batch, self._q = self._q, []
                self._event.clear()
            if not batch:
                continue
            try:
                self.core.io.run(self.raylet.call_retrying(
                    "objects_sealed_batch", {"objects": batch},
                    attempts=5, per_try_timeout=2.0))
            except Exception:
                # a lost seal notification would strand every consumer
                # of these objects in the directory: REQUEUE and keep
                # trying (the raylet being down this long usually means
                # the node is dying anyway — but never silently drop)
                with self._lock:
                    self._q = batch + self._q
                self._event.set()
                _time.sleep(1.0)


class TaskExecutor:
    def __init__(self, core: CoreWorker, raylet: RpcClient):
        self.core = core
        self.raylet = raylet
        self.seal_batcher: Optional[SealBatcher] = None
        # the worker's flight recorder (blackbox.py), if enabled — the
        # deliberate-exit paths close it so an ORDERED kill (force
        # cancel, kill_self) never masquerades as a crash bundle
        self.blackbox_rec = None
        self.pool = ThreadPoolExecutor(max_workers=4, thread_name_prefix="task_exec")
        self._applied_env: dict = {}  # runtime-env hash this worker adopted
        # actor runtime
        self.actor_instance: Any = None
        self.actor_id = None
        self.actor_async = False
        self._actor_loop_obj = None
        self._actor_sem = None
        self._actor_queue: "queue.Queue" = queue.Queue()
        self._actor_threads: List[threading.Thread] = []
        # cancellation: task_id -> executing thread (ref: _raylet.pyx
        # execute_task_with_cancellation_handler); requests arriving before
        # the task registers (still loading its function) are parked
        self._running: dict = {}
        self._cancel_requested: set = set()
        # tasks that already finished here, kept briefly so a late
        # cancel() — e.g. a hedge loser whose reply raced the winner's
        # cancel RPC — is a silent no-op instead of parking forever in
        # _cancel_requested (bounded: deque evicts, set membership-tests)
        self._recently_done: "collections.deque" = collections.deque(
            maxlen=1024)
        self._recently_done_set: set = set()
        # streaming: task_id -> producer budget
        self._gen_budgets: dict = {}
        # stall sentinel: task_id -> (thread ident, fn name, started at);
        # feeds dump_stacks (stack annotation) and stall_probe (the
        # raylet watchdog's RUNNING-age / per-class EMA inputs)
        self._running_since: dict = {}
        # (fn name, duration) of completions since the last stall_probe
        self._completed_durations: List[Tuple[str, float]] = []
        self._durations_lock = locking.make_lock("TaskExecutor._durations_lock")
        # profiling plane (util/stacks.py): an always-on ambient sampler
        # (profiling_sample_hz > 0) plus an on-demand burst sampler the
        # profile_start/profile_stop RPCs drive; task-thread samples are
        # rooted "task:<fn>" so the GCS can merge per scheduling class
        self._ambient_sampler: Optional[stacks.StackSampler] = None
        self._burst_sampler: Optional[stacks.StackSampler] = None
        self._hbm_last_report = 0.0

    def _register_running(self, task_id, fn_name: str = "") -> None:
        """Bind the executing thread; honor a cancel that raced startup."""
        self._running[task_id] = threading.current_thread()
        self._running_since[task_id] = (
            threading.get_ident(), fn_name, time.time())
        if task_id in self._cancel_requested:
            self._cancel_requested.discard(task_id)
            raise exc.TaskCancelledError("task cancelled before start")

    def _unregister_running(self, task_id) -> None:
        self._running.pop(task_id, None)
        if len(self._recently_done) == self._recently_done.maxlen:
            self._recently_done_set.discard(self._recently_done[0])
        self._recently_done.append(task_id)
        self._recently_done_set.add(task_id)
        entry = self._running_since.pop(task_id, None)
        if entry is not None:
            with self._durations_lock:
                self._completed_durations.append(
                    (entry[1], time.time() - entry[2]))
                # bound the backlog if no watchdog ever drains it
                if len(self._completed_durations) > 512:
                    del self._completed_durations[:256]

    # ------------------------------------------------------ stall sentinel
    def stall_probe(self) -> dict:
        """Cheap watchdog input: tasks currently RUNNING on this worker
        (with age) plus completed (fn, duration) samples drained since
        the last probe — the raylet's per-scheduling-class EMA feed."""
        now = time.time()
        with self._durations_lock:
            completed, self._completed_durations = \
                self._completed_durations, []
        running = [
            {"task_id": tid.hex(), "fn": fn, "age_s": now - t0}
            for tid, (_, fn, t0) in list(self._running_since.items())
        ]
        self._maybe_report_hbm()
        return {"pid": os.getpid(), "running": running,
                "completed": completed}

    def dump_stacks(self) -> dict:
        """sys._current_frames() snapshot, each thread annotated with the
        task it is executing (if any) and its time-in-state. The remote
        half of `cli.py stacks` and the watchdogs' hang forensics.
        Capture/annotation lives in util/stacks.py, shared with the
        sampling profiler (one format, one annotation path)."""
        now = time.time()
        return {
            "pid": os.getpid(),
            "worker_id": self.core.worker_id.hex(),
            "actor_id": self.actor_id.hex() if self.actor_id else None,
            "time": now,
            "threads": stacks.capture_threads(self._running_since, now=now),
        }

    # -------------------------------------------------- sampling profiler
    def _annotate_thread(self, ident: int) -> Optional[str]:
        """Root label for a sampled thread: the task it is executing (the
        sampler's per-scheduling-class merge handle), None otherwise."""
        for _tid, (tident, fn, _t0) in list(self._running_since.items()):
            if tident == ident:
                return f"task:{fn or '?'}"
        return None

    def start_ambient_sampler(self, hz: float) -> None:
        """Always-on low-rate mode (profiling_sample_hz knob)."""
        if hz <= 0 or self._ambient_sampler is not None:
            return
        self._ambient_sampler = stacks.StackSampler(
            hz, annotate=self._annotate_thread,
            max_depth=global_config().profiling_max_stack_depth,
            name="stack_sampler").start()

    def profile_start(self, hz: float) -> bool:
        """On-demand burst capture; a second start supersedes the first
        (the previous burst's thread is joined, its samples dropped)."""
        if self._burst_sampler is not None:
            self._burst_sampler.stop(timeout=1.0)
        self._burst_sampler = stacks.StackSampler(
            hz, annotate=self._annotate_thread,
            max_depth=global_config().profiling_max_stack_depth,
            name="stack_sampler_burst").start()
        return True

    def profile_stop(self) -> dict:
        """End the burst (or drain the ambient accumulation when no
        burst is running) and return the folded-stack snapshot."""
        burst, self._burst_sampler = self._burst_sampler, None
        if burst is not None:
            burst.stop(timeout=2.0)
            snap = burst.snapshot()
        elif self._ambient_sampler is not None:
            snap = self._ambient_sampler.snapshot(reset=True)
        else:
            snap = {"pid": os.getpid(), "hz": 0.0, "samples": 0,
                    "duration_s": 0.0, "wall": {}, "cpu": {}}
        snap["worker_id"] = self.core.worker_id.hex()
        snap["actor_id"] = self.actor_id.hex() if self.actor_id else None
        return snap

    def _maybe_report_hbm(self) -> None:
        """Rate-limited HBM gauge publication, piggybacked on the
        watchdog's stall_probe tick (no extra thread, no RPC). Inert
        until task code actually initializes jax in this process."""
        if "jax" not in sys.modules:
            return
        interval = global_config().hbm_gauge_interval_s
        if interval <= 0:
            return
        now = time.monotonic()
        if now - self._hbm_last_report < interval:
            return
        self._hbm_last_report = now
        try:
            from ..util import hbm

            hbm.publish_hbm_gauges(node=self.core.node_id.hex()[:12])
        except Exception:  # graftlint: ignore[swallow] — HBM gauges are
            pass           # best-effort; a backend hiccup can't kill
            # the worker main loop that publishes them

    # ---------------------------------------------------------- arg loading
    def _resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        args, kwargs = [], {}
        # gather deps first so we wait once; small objects come from
        # their owner (never sealed into plasma), the rest through the
        # raylet directory/pull path
        ref_args = [a for a in spec.args if a.kind == ArgKind.OBJECT_REF]
        missing = [a for a in ref_args
                   if not self.core.store.contains(a.object_id)
                   and not self.core.memory_store.contains(a.object_id)]
        if missing:
            # dep wait: release the lease's CPU for the duration, or a
            # gang of dep-blocked workers deadlocks the node (ref:
            # NotifyDirectCallTaskBlocked)
            self.core._notify_blocked()
        try:
            plasma_wait = []
            for a in missing:
                if a.owner and a.owner != self.core.address:
                    status = self.core.io.run(self.core._fetch_from_owner(
                        a.owner, a.object_id, None))
                    if status == "ok":
                        continue
                    # "gone"/"unreachable": the object may still be
                    # sealed in plasma on a third node — directory wait
                plasma_wait.append(a.object_id)
            if plasma_wait:
                self.core.io.run(self.core.raylet.call("wait_objects", {
                    "object_ids": plasma_wait,
                    "num_returns": len(plasma_wait),
                    "timeout": None,
                    "prio": 0,  # this worker is blocked on its task args
                }))
        finally:
            if missing:
                self.core._notify_unblocked()
        for arg in spec.args:
            if arg.kind == ArgKind.VALUE:
                kw, data = arg.value
                value, _ = ser.deserialize(data)
            else:
                kw = arg.value
                value = self.core._load_object(arg.object_id)
            if kw is None:
                args.append(value)
            else:
                kwargs[kw] = value
        return args, kwargs

    # -------------------------------------------------------- result sealing
    def _ok_reply(self, spec: TaskSpec, values: Any) -> dict:
        results, sealed = self._seal_results(spec, values)
        if not spec.is_actor_task():
            # actor calls don't flow through the task table (no SUBMITTED
            # record exists for them) — don't create orphan records
            self.core._record_transition(spec.task_id, "OUTPUT_SEALED")
        return {"results": results, "sealed": sealed, "error": None}

    def _seal_results(self, spec: TaskSpec, values: Any) -> tuple:
        small_limit = global_config().object_store_small_object_threshold
        if spec.num_returns == 0:
            return [], []
        if spec.num_returns == 1:
            values = (values,)
        elif not isinstance(values, tuple):
            values = tuple(values)
        results = []
        sealed = []
        for i, value in enumerate(values[: spec.num_returns]):
            oid = ObjectID.for_return(spec.task_id, i + 1)
            data = ser.serialize(value)
            if len(data) <= small_limit:
                # small returns ride the reply into the owner's memory
                # store and are served from there (fetch_object); no
                # plasma write, no directory entry (ref: the reference's
                # in-process store for inlined returns)
                results.append((oid, data))
            else:
                self.core.store.put(oid, data)
                self._notify_sealed(oid, len(data))
                results.append((oid, None))
                # rides the reply so the owner learns where (and how big)
                # its large returns are — locality-aware leasing input
                sealed.append((oid, len(data)))
        return results, sealed

    def _notify_sealed(self, oid: ObjectID, size: int) -> None:
        # idempotent + retried: a lost seal notification would strand every
        # consumer waiting on this object in the directory
        if self.seal_batcher is not None:
            self.seal_batcher.add(oid, size)
            return
        self.core.io.run(self.raylet.call_retrying(
            "object_sealed", {"object_id": oid, "size": size},
            attempts=5, per_try_timeout=2.0))

    def _seal_error(self, spec: TaskSpec, error: BaseException) -> bytes:
        data = ser.serialize_error(error)
        for oid in spec.return_ids():
            self.core.store.put(oid, data)
            self._notify_sealed(oid, len(data))
        return data

    # ------------------------------------------------------------ execution
    def _ensure_runtime_env(self, spec: TaskSpec) -> None:
        from .runtime_env import apply_runtime_env

        self._apply_chip_visibility(spec)
        apply_runtime_env(self.core, spec.runtime_env, self._applied_env)

    def _apply_chip_visibility(self, spec: TaskSpec) -> None:
        """Export the lease's physical chip set before user code runs
        (ref: accelerators/tpu.py:31 TPU_VISIBLE_CHIPS — here the ids
        come from the raylet's per-lease chip accounting, so two
        fractional-host leases on one machine see disjoint chips).
        Effective for code that initializes jax after this point; the
        pool worker itself stays CPU-pinned for the control plane."""
        if spec.chip_ids is None:
            # chipless task on a reused pool worker: stale visibility
            # from a PREVIOUS lease must not leak (the chips may belong
            # to someone else now)
            os.environ.pop("TPU_VISIBLE_CHIPS", None)
            os.environ.pop("RAY_TPU_CHIP_IDS", None)
            return
        ids = ",".join(str(i) for i in spec.chip_ids)
        os.environ["TPU_VISIBLE_CHIPS"] = ids
        os.environ["RAY_TPU_CHIP_IDS"] = ids

    def execute_normal(self, spec: TaskSpec) -> dict:
        try:
            self._ensure_runtime_env(spec)
            func = self.core.load_function(spec.function.blob_id)
            self.core._record_transition(spec.task_id, "PENDING_ARGS_FETCH")
            args, kwargs = self._resolve_args(spec)
            self.core.set_task_context(spec.task_id)
            self._register_running(spec.task_id, spec.function.repr_name)
            self.core._record_transition(spec.task_id, "RUNNING")
            try:
                # inside the RUNNING window so injected straggle shows up
                # in stall_probe age and trips the raylet watchdog
                failpoints.fire("worker.task.run",
                                detail=os.environ.get("RAY_TPU_NODE_ID"))
                with _maybe_span(spec):
                    if spec.runtime_env and spec.runtime_env.get(
                            "container"):
                        from .runtime_env import run_task_in_container

                        values = run_task_in_container(
                            spec.runtime_env["container"], func, args,
                            kwargs,
                            env_vars=spec.runtime_env.get("env_vars"))
                    else:
                        values = func(*args, **kwargs)
            finally:
                self._unregister_running(spec.task_id)
                self.core.clear_task_context()
            return self._ok_reply(spec, values)
        except BaseException as e:  # noqa: BLE001
            return {"results": [], "error": self._seal_error(spec, e)}

    def cancel(self, task_id, force: bool) -> bool:
        """Interrupt a running task: TaskCancelledError is raised at the next
        bytecode boundary of its thread (force: the process exits). A task
        still in startup (function load / arg fetch) is marked so it raises
        the moment it registers."""
        if force:
            if self.blackbox_rec is not None:
                self.blackbox_rec.close(clean=True)
            threading.Timer(0.02, lambda: os._exit(1)).start()
            return True
        thread = self._running.get(task_id)
        if thread is None or not thread.is_alive():
            if task_id in self._recently_done_set:
                # already sealed (hedge loser, or cancel racing normal
                # completion): nothing to interrupt, nothing to park
                return True
            self._cancel_requested.add(task_id)
            return False
        import ctypes

        n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread.ident),
            ctypes.py_object(exc.TaskCancelledError))
        return n == 1

    def execute_streaming(self, spec: TaskSpec, push) -> dict:
        """Run a generator task, sealing + reporting each item eagerly
        (ref: _raylet.pyx:1138-1225 streaming generator returns). ``push``
        delivers one ordered frame to the owner and blocks until written."""
        import inspect

        small_limit = global_config().object_store_small_object_threshold
        budget = self._gen_budgets[spec.task_id] = _GenBudget(
            spec.backpressure_items)
        index = 0

        def _emit(data: bytes) -> None:
            nonlocal index
            index += 1
            oid = ObjectID.for_return(spec.task_id, index)
            self.core.store.put(oid, data)
            self._notify_sealed(oid, len(data))
            push({"task_id": spec.task_id, "index": index, "object_id": oid,
                  "data": data if len(data) <= small_limit else None,
                  "done": False, "worker_address": self.core.address})

        try:
            try:
                self._ensure_runtime_env(spec)
                func = self.core.load_function(spec.function.blob_id)
                self.core._record_transition(spec.task_id,
                                             "PENDING_ARGS_FETCH")
                args, kwargs = self._resolve_args(spec)
                self.core.set_task_context(spec.task_id)
                self._register_running(spec.task_id,
                                       spec.function.repr_name)
                self.core._record_transition(spec.task_id, "RUNNING")
                try:
                    out = func(*args, **kwargs)
                    items = out if inspect.isgenerator(out) else iter([out])
                    for value in items:
                        _emit(ser.serialize(value))
                        budget.wait_for_budget(index)
                finally:
                    self._unregister_running(spec.task_id)
                    self.core.clear_task_context()
            except BaseException as e:  # noqa: BLE001 — errors ride the stream
                _emit(ser.serialize_error(e))
            push({"task_id": spec.task_id, "done": True, "total": index,
                  "worker_address": self.core.address})
            return {"results": [], "error": None}
        finally:
            self._gen_budgets.pop(spec.task_id, None)

    def execute_actor_creation(self, spec: TaskSpec) -> dict:
        try:
            import inspect

            self._ensure_runtime_env(spec)
            cls = self.core.load_function(spec.function.blob_id)
            if hasattr(cls, "__ray_tpu_actor_class__"):
                cls = cls.__ray_tpu_actor_class__
            args, kwargs = self._resolve_args(spec)
            self.actor_instance = cls(*args, **kwargs)
            self.actor_id = spec.actor_id
            # async actors: any coroutine method promotes the actor to an
            # asyncio runtime — methods interleave at await points, bounded
            # by max_concurrency (ref: _raylet.pyx async actor path /
            # core_worker fiber.h; reference default concurrency is 1000)
            self.actor_async = any(
                inspect.iscoroutinefunction(m)
                for _, m in inspect.getmembers(type(self.actor_instance),
                                               inspect.isfunction))
            if self.actor_async:
                concurrency = (spec.actor_max_concurrency
                               if spec.actor_max_concurrency > 0 else 1000)
                self._actor_loop_obj = asyncio.new_event_loop()
                self._actor_sem = None  # created on the actor loop
                self._actor_concurrency = concurrency

                def _loop_main():
                    asyncio.set_event_loop(self._actor_loop_obj)
                    self._actor_sem = asyncio.Semaphore(concurrency)
                    self._actor_loop_obj.run_forever()

                t = threading.Thread(target=_loop_main, daemon=True,
                                     name="actor_asyncio")
                t.start()
                self._actor_threads.append(t)
            else:
                n_threads = max(1, spec.actor_max_concurrency or 1)
                for i in range(n_threads):
                    t = threading.Thread(target=self._actor_loop, daemon=True,
                                         name=f"actor_exec_{i}")
                    t.start()
                    self._actor_threads.append(t)
            return {"results": [], "error": None}
        except BaseException as e:  # noqa: BLE001
            return {"results": [], "error": self._seal_error(spec, e)}

    async def execute_actor_task_async(self, spec: TaskSpec) -> dict:
        """One actor task on the actor's asyncio loop. Blocking work
        (plasma arg fetch, large-result sealing) goes to the thread pool
        so thousands of calls can park at await points — but the COMMON
        async call (small VALUE args, one small return) runs entirely on
        the loop: two run_in_executor hops per call were the async
        lane's throughput ceiling (~4.5k/s vs ~10.6k/s sync; each hop is
        a thread handoff both ways)."""
        loop = asyncio.get_event_loop()
        while self._actor_sem is None:  # loop thread still starting
            await asyncio.sleep(0.001)
        async with self._actor_sem:
            try:
                # run_coroutine_threadsafe gave this task its own Context,
                # so the binding is visible to this coroutine only
                self.core.set_async_task_context(spec.task_id)
                method = _resolve_actor_method(
                    self.actor_instance, spec.function.method_name)
                if all(a.kind == ArgKind.VALUE for a in spec.args):
                    # pure-value args: deserialization is loop-cheap
                    args, kwargs = self._resolve_args(spec)
                else:
                    args, kwargs = await loop.run_in_executor(
                        self.pool, self._resolve_args, spec)
                with _maybe_span(spec):
                    values = method(*args, **kwargs)
                    if asyncio.iscoroutine(values):
                        values = await values
                small = global_config().object_store_small_object_threshold
                if spec.num_returns == 1 and _cheap_size_bound(values, small):
                    data = ser.serialize(values)
                    if len(data) <= small:
                        oid = ObjectID.for_return(spec.task_id, 1)
                        return {"results": [(oid, data)], "sealed": [],
                                "error": None}
                    # the bound was optimistic (e.g. a dict that pickles
                    # big): only the plasma write leaves the loop
                    def _seal_large():
                        oid = ObjectID.for_return(spec.task_id, 1)
                        self.core.store.put(oid, data)
                        self._notify_sealed(oid, len(data))
                        return {"results": [(oid, None)],
                                "sealed": [(oid, len(data))], "error": None}
                    return await loop.run_in_executor(self.pool, _seal_large)
                return await loop.run_in_executor(
                    self.pool, lambda: self._ok_reply(spec, values))
            except BaseException as e:  # noqa: BLE001
                return {"results": [],
                        "error": await loop.run_in_executor(
                            self.pool, self._seal_error, spec, e)}

    def _actor_loop(self):
        while True:
            item = self._actor_queue.get()
            if item is None:
                return
            spec, reply_cb = item
            reply = self._execute_actor_task(spec)
            reply_cb(reply)

    def _execute_actor_task(self, spec: TaskSpec) -> dict:
        try:
            method = _resolve_actor_method(
                self.actor_instance, spec.function.method_name)
            args, kwargs = self._resolve_args(spec)
            self.core.set_task_context(spec.task_id)
            # stall-sentinel annotation only (not self._running — actor
            # cancellation semantics stay unchanged)
            self._running_since[spec.task_id] = (
                threading.get_ident(), spec.function.repr_name,
                time.time())
            try:
                with _maybe_span(spec):
                    values = method(*args, **kwargs)
            finally:
                self._unregister_running(spec.task_id)
                self.core.clear_task_context()
            if asyncio.iscoroutine(values):
                values = asyncio.get_event_loop_policy().new_event_loop().run_until_complete(values)
            return self._ok_reply(spec, values)
        except BaseException as e:  # noqa: BLE001
            return {"results": [], "error": self._seal_error(spec, e)}


async def _amain():
    session = os.environ["RAY_TPU_SESSION"]
    raylet_socket = os.environ["RAY_TPU_RAYLET_SOCKET"]
    gcs_socket = os.environ["RAY_TPU_GCS_SOCKET"]
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])
    worker_id = WorkerID.from_random()
    cfg = global_config()

    if "/" in raylet_socket:
        session_dir = os.path.dirname(raylet_socket)
        my_socket = os.path.join(session_dir, f"worker_{worker_id.hex()[:16]}.sock")
    else:
        my_socket = "127.0.0.1:0"  # TCP node: serve on an ephemeral port

    store_ns = os.environ.get("RAY_TPU_STORE_DIR", session)
    store = SharedObjectStore(store_ns, cfg.object_store_memory_bytes, create_dir=False)
    # the core worker shares this process's running loop
    from .rpc import EventLoopThread

    loop = asyncio.get_event_loop()

    class _LoopShim:
        """EventLoopThread interface over the already-running worker loop."""

        def __init__(self, loop):
            self.loop = loop
            # _amain's loop runs on the worker's main thread; callers
            # (e.g. kill_actor) compare against .thread to pick the
            # non-deadlocking submission path, same as EventLoopThread
            self.thread = threading.main_thread()

        def run(self, coro, timeout=None):
            import concurrent.futures as cf

            if threading.current_thread() is threading.main_thread():
                # called from the loop thread itself — must never happen for
                # blocking calls; execute as a task and let caller await
                raise RuntimeError("blocking io.run on loop thread")
            fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
            return fut.result(timeout)

        def spawn(self, coro):
            return asyncio.run_coroutine_threadsafe(coro, self.loop)

        def stop(self):
            pass

    core = CoreWorker(
        mode="worker",
        session_name=session,
        gcs_address=gcs_socket,
        raylet_address=raylet_socket,
        job_id=JobID.from_int(0),
        node_id=node_id,
        store=store,
        io=_LoopShim(loop),
        worker_id=worker_id,
    )
    core.address = my_socket
    await core._connect()
    # user code inside tasks reaches the runtime through the module-level API
    from .. import _worker_api

    _worker_api._core = core

    raylet = RpcClient(raylet_socket)
    await raylet.connect()

    executor = TaskExecutor(core, raylet)
    # read AFTER _connect(): _system_config overrides land there
    if cfg.profiling_sample_hz > 0:
        executor.start_ambient_sampler(cfg.profiling_sample_hz)
    blackbox_rec = None
    if cfg.blackbox_enabled:
        # black-box flight ring: running on the MAIN thread here, so the
        # SIGTERM/SIGABRT dump handlers actually install (unlike raylet/
        # GCS, which live on an event-loop thread and rely on the
        # survivor sweep); a SIGKILL'd worker leaves its last flushed
        # flight file for the raylet to promote on disconnect
        from .config import TEMP_ROOT
        from . import blackbox
        from ..util import metrics as _metrics

        def _bb_inflight():
            now = time.time()
            return [
                {"kind": "task", "task_id": tid.hex(), "fn": fn,
                 "age_s": round(now - t0, 3)}
                for tid, (_, fn, t0) in
                list(executor._running_since.items())
            ]

        blackbox_rec = blackbox.FlightRecorder(
            "worker", os.path.join(TEMP_ROOT, session),
            ident=worker_id.hex(), node_id=node_id.hex(),
            ring_size=cfg.blackbox_ring_size,
            flush_interval_s=cfg.blackbox_flush_interval_s,
            inflight_provider=_bb_inflight,
            stacks_provider=lambda: stacks.flight_snapshot(
                executor._running_since),
            metrics_provider=lambda: _metrics.snapshot_local())
        blackbox_rec.start()
        executor.blackbox_rec = blackbox_rec
        logging.getLogger("ray_tpu").addHandler(
            blackbox.RingLogHandler(blackbox_rec))
    if cfg.tracemalloc_enabled:
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
    server = RpcServer(my_socket, name=f"worker-{worker_id.hex()[:8]}")
    shutdown_event = asyncio.Event()

    async def handle_push_task(payload, conn):
        spec: TaskSpec = cloudpickle.loads(payload)
        if not spec.actor_creation and not spec.is_actor_task():
            # worker-start mark: transitions-only (never the top-level
            # `state` field — a flush race with the owner's terminal
            # event must not clobber FINISHED/FAILED)
            core._record_transition(spec.task_id, "WORKER_STARTED")
        if spec.actor_creation:
            core.job_id = spec.job_id
            core.current_task_id = spec.task_id
            reply = await loop.run_in_executor(executor.pool,
                                               executor.execute_actor_creation, spec)
            if reply["error"] is None:
                await core.gcs.call("actor_alive", {
                    "actor_id": spec.actor_id,
                    "address": my_socket,
                    "node_id": node_id,
                })
            return reply
        if spec.is_actor_task():
            if getattr(executor, "actor_async", False):
                afut = asyncio.run_coroutine_threadsafe(
                    executor.execute_actor_task_async(spec),
                    executor._actor_loop_obj)
                return await asyncio.wrap_future(afut)
            fut = loop.create_future()

            def reply_cb(result, fut=fut):
                loop.call_soon_threadsafe(
                    lambda: fut.set_result(result) if not fut.done() else None)

            executor._actor_queue.put((spec, reply_cb))
            return await fut
        core.job_id = spec.job_id
        if spec.streaming:
            def push(frame, conn=conn):
                # called from the generator thread; blocking on the loop-side
                # write keeps frames ordered and paces the producer
                asyncio.run_coroutine_threadsafe(
                    conn.push("generator_item", frame), loop).result()

            return await loop.run_in_executor(
                executor.pool, executor.execute_streaming, spec, push)
        return await loop.run_in_executor(executor.pool, executor.execute_normal, spec)

    async def handle_cancel_task(payload, conn):
        return executor.cancel(payload["task_id"], payload.get("force", False))

    async def handle_generator_ack(payload, conn):
        budget = executor._gen_budgets.get(payload["task_id"])
        if budget is not None:
            budget.ack(payload["consumed"])
        return True

    async def handle_kill_self(payload, conn):
        if executor.blackbox_rec is not None:
            executor.blackbox_rec.close(clean=True)
        loop.call_later(0.05, lambda: os._exit(0))
        return True

    def _lane_serve(sub, rep, kind: str):
        """Fast-lane server thread: pop task frames (single or batched)
        off the shm ring, execute, push replies
        (ray_tpu/_private/fastlane.py). Normal tasks run inline on this
        thread (the lane is one serial worker, like a leased worker in
        the reference); actor tasks route into the actor runtime so
        ordering and concurrency semantics match the asyncio path
        exactly."""
        import pickle as _pickle

        def send(seq: int, reply: dict) -> None:
            try:
                rep.push(_pickle.dumps((seq, reply), protocol=5),
                         timeout_ms=5000)
            except (BrokenPipeError, ValueError):
                pass

        async def _run_async_one(seq: int, spec) -> None:
            try:
                reply = await executor.execute_actor_task_async(spec)
            except BaseException as e:  # noqa: BLE001
                reply = {"results": [],
                         "error": executor._seal_error(spec, e)}
            send(seq, reply)

        async def _run_async_batch(items) -> None:
            # created in submission order on ONE loop tick, so per-caller
            # ordering of task STARTS matches the sync lane; awaits may
            # interleave (async-actor semantics)
            await asyncio.gather(*(
                _run_async_one(seq, spec) for seq, spec in items))

        def serve_batch_async(items) -> None:
            """One threadsafe loop wakeup per ring frame instead of one
            per call — the async lane's remaining per-call overhead."""
            asyncio.run_coroutine_threadsafe(
                _run_async_batch(items), executor._actor_loop_obj)

        def serve_one(seq: int, spec) -> None:
            if kind == "actor" and spec.is_actor_task():
                if getattr(executor, "actor_async", False):
                    serve_batch_async([(seq, spec)])
                else:
                    executor._actor_queue.put(
                        (spec, lambda reply, seq=seq: send(seq, reply)))
            else:
                core.job_id = spec.job_id
                send(seq, executor.execute_normal(spec))

        try:
            while True:
                try:
                    frame = sub.pop(timeout_ms=500)
                except (BrokenPipeError, ValueError):
                    break
                if frame is None:
                    continue
                try:
                    batch = _pickle.loads(frame)
                except Exception:
                    continue
                if not isinstance(batch, list):
                    batch = [batch]
                if (kind == "actor" and getattr(executor, "actor_async",
                                                False) and len(batch) > 1
                        and all(s.is_actor_task() for _, s in batch)):
                    serve_batch_async(batch)
                else:
                    for seq, spec in batch:
                        serve_one(seq, spec)
        finally:
            try:
                rep.close_write()
            except Exception:
                pass
            if kind == "task":
                # only this thread ever touched the rings: drop the
                # mappings (the owner unlinks the files). Actor lanes
                # skip this — in-flight calls may still push replies
                # from actor threads; the mappings die with the process.
                for ring in (sub, rep):
                    try:
                        ring.free()
                    except Exception:
                        pass

    async def handle_fastlane_attach(payload, conn):
        try:
            from .._native import Ring

            sub = Ring(payload["sub"])
            rep = Ring(payload["rep"])
        except Exception:
            return False
        threading.Thread(
            target=_lane_serve, args=(sub, rep, payload.get("kind", "task")),
            daemon=True, name="fastlane_serve").start()
        return True

    async def handle_health(payload, conn):
        return {"pid": os.getpid(), "actor": executor.actor_id}

    async def handle_dump_stacks(payload, conn):
        # runs on the event loop, not a task thread — the loop itself
        # stays responsive even while every executor thread is wedged,
        # which is exactly when this RPC matters
        return executor.dump_stacks()

    async def handle_stall_probe(payload, conn):
        return executor.stall_probe()

    async def handle_profile_start(payload, conn):
        return executor.profile_start(float(payload.get("hz", 100.0)))

    async def handle_profile_stop(payload, conn):
        # like dump_stacks: served from the event loop so a cluster
        # profile still answers while every executor thread is busy
        return executor.profile_stop()

    async def handle_memory_report(payload, conn):
        return core.local_memory_report()

    server.register("push_task", handle_push_task)
    server.register("cancel_task", handle_cancel_task)
    server.register("generator_ack", handle_generator_ack)
    server.register("kill_self", handle_kill_self)
    server.register("health", handle_health)
    server.register("dump_stacks", handle_dump_stacks)
    server.register("stall_probe", handle_stall_probe)
    server.register("profile_start", handle_profile_start)
    server.register("profile_stop", handle_profile_stop)
    server.register("memory_report", handle_memory_report)
    server.register("fastlane_attach", handle_fastlane_attach)
    # owner-serve: this worker's owned small objects (nested submissions)
    server.register("fetch_object", core._handle_fetch_object)
    # nested submissions from this worker can hedge too — the raylet
    # watchdog's hint must reach whatever process owns the task
    server.register("hedge_hint", core.handle_hedge_hint)
    executor.seal_batcher = SealBatcher(core, raylet)
    await server.start()
    try:
        my_socket = server.address  # resolved (TCP port 0)
        core.address = my_socket

        # register with raylet last — once registered, tasks may arrive
        raylet.on_push("shutdown", lambda payload: shutdown_event.set())
        # die with the raylet: an abrupt raylet death (SIGKILL, node
        # crash) sends no shutdown push, and an orphaned worker would
        # outlive the whole cluster (ref: core_worker shuts down when
        # the local raylet connection breaks). call_soon_threadsafe not
        # needed — the recv loop runs on this same loop.
        raylet.on_close = shutdown_event.set
        await raylet.call("register_worker", {
            "worker_id": worker_id,
            "pid": os.getpid(),
            "address": my_socket,
        })

        await shutdown_event.wait()
    finally:
        # a failed registration must still unbind the socket before the
        # process exits, or a fast raylet retry can hit a stale address
        await server.stop()
    if blackbox_rec is not None:
        blackbox_rec.close(clean=True)  # ordered shutdown: no corpse
    os._exit(0)


def main():
    try:
        asyncio.run(_amain())
    except KeyboardInterrupt:  # graftlint: ignore[swallow] — quiet ^C exit
        pass


if __name__ == "__main__":
    main()
