"""Forkserver-style worker factory: pay the interpreter+import cost once,
fork per worker.

TPU-native analog of the reference worker-pool prestart path (ref:
src/ray/raylet/worker_pool.h PopWorker/PrestartWorkers — the reference
amortizes worker startup by keeping warm processes; here the whole warm
interpreter is amortized). A cold `python -m ray_tpu._private.worker_main`
costs ~0.7 s of imports per worker; at envelope depth (1k+ live actors on a
host, release/benchmarks/README.md:10) that is the difference between
seconds and tens of minutes. The factory imports the full worker stack
once, then serves fork requests over a unix socket at ~10 ms each, with
copy-on-write sharing of the imported interpreter between workers.

Protocol (newline-delimited JSON over a unix stream socket):
    -> {"cmd": "spawn", "log_path": "...", "env": {k: v|null, ...}}
    <- {"pid": 1234} | {"error": "..."}
    -> {"cmd": "ping"}            <- {"ok": true}
    -> {"cmd": "exit"}            (factory exits; forked workers survive)

The factory is strictly single-threaded — forking a multithreaded process
can deadlock the child on locks held by threads that do not survive the
fork, so no event loop, thread pool, or background thread may start before
fork time. The forked child resets per-process state (config cache, RNG)
and runs ``worker_main.main()`` exactly as a cold-started worker would.
"""

from __future__ import annotations

import json
import os
import random
import socket
import sys
import traceback


def _reap() -> None:
    """Collect exited workers (they are this process's children)."""
    while True:
        try:
            pid, _ = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return


def _fork_worker(req: dict, listener: socket.socket,
                 conn: socket.socket) -> int:
    pid = os.fork()
    if pid:
        return pid
    # ---- child: become a fresh worker process ----
    code = 1
    try:
        os.setsid()  # detach: factory exit must not signal workers
        listener.close()
        conn.close()
        log_path = req.get("log_path")
        if log_path:
            fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            os.close(fd)
        for key, value in (req.get("env") or {}).items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = str(value)
        # sys.path was fixed at FACTORY interpreter start; the spawn's
        # PYTHONPATH (driver sys.path additions, runtime-env py_modules/
        # working_dir) must reach this worker's import system or its
        # tasks fail on driver-local modules a cold-started worker would
        # see. Prepend missing entries, preserving their order.
        pythonpath = os.environ.get("PYTHONPATH", "")
        have = set(sys.path)
        for i, entry in enumerate(p for p in pythonpath.split(os.pathsep)
                                  if p and p not in have):
            sys.path.insert(i, entry)
        # the factory's cached config snapshotted ITS env, not this
        # worker's; and forked children share the parent's Mersenne
        # state — identical "random" streams across the pool otherwise
        from .config import reset_global_config

        reset_global_config()
        random.seed(os.urandom(16))
        from . import worker_main

        worker_main.main()
        code = 0
    except BaseException:
        traceback.print_exc()
    finally:
        # never unwind into factory code (atexit hooks, finally blocks of
        # the accept loop) from a forked child
        os._exit(code)


def _serve_conn(conn: socket.socket, listener: socket.socket) -> bool:
    """Handle requests from one raylet connection until EOF.
    Returns False when the factory should exit.

    The raylet connection is persistent, so this loop — not the accept
    loop — is where the factory spends its life; zombie reaping and the
    orphan check must run here too (idle periods after worker churn
    would otherwise accumulate exited children indefinitely). Framing is
    buffered by hand: a stdlib BufferedReader would hide bytes from
    select() and peek() can block, so select-then-recv is the only
    combination that is both line-complete and idle-interruptible."""
    import select

    buf = bytearray()
    try:
        while True:
            line_end = buf.find(b"\n")
            if line_end < 0:
                ready, _, _ = select.select([conn], [], [], 1.0)
                if not ready:
                    _reap()
                    if os.getppid() == 1:
                        return False  # raylet process died without "exit"
                    continue
                chunk = conn.recv(65536)
                if not chunk:
                    break  # EOF: raylet closed the connection
                buf += chunk
                continue
            line = bytes(buf[:line_end]).strip()
            del buf[:line_end + 1]
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError:
                break  # corrupt stream: drop the connection
            cmd = req.get("cmd")
            if cmd == "spawn":
                try:
                    reply = {"pid": _fork_worker(req, listener, conn)}
                except OSError as e:
                    reply = {"error": f"fork failed: {e}"}
            elif cmd == "ping":
                reply = {"ok": True}
            elif cmd == "exit":
                return False
            else:
                reply = {"error": f"unknown cmd: {cmd!r}"}
            conn.sendall(json.dumps(reply).encode() + b"\n")
            _reap()
    except (BrokenPipeError, ConnectionResetError, OSError):
        pass
    finally:
        try:
            conn.close()
        except Exception:
            pass
    return True


def main() -> None:
    sock_path = os.environ["RAY_TPU_FACTORY_SOCKET"]
    # Pay the full worker import bill now, before binding: a connectable
    # socket is the readiness signal, so every fork after it is warm.
    from . import worker_main  # noqa: F401

    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(8)
    listener.settimeout(1.0)
    try:
        while True:
            _reap()
            # orphaned (raylet process died without "exit"): quit rather
            # than linger as a session leak; forked workers are their own
            # sessions and die through the raylet-connection path instead
            if os.getppid() == 1:
                return
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if not _serve_conn(conn, listener):
                return
    finally:
        listener.close()
        try:
            os.unlink(sock_path)
        except OSError:
            pass


if __name__ == "__main__":
    main()
