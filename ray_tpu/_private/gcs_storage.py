"""GCS persistence seam: pluggable store clients behind one Storage facade.

TPU-native analog of the reference store-client layer (ref:
src/ray/gcs/store_client/store_client.h:33 — the interface;
in_memory_store_client.h — volatile; redis_store_client.h:111 — the
external persistent backend the reference leans on for head fault
tolerance; observer wiring gcs/gcs_server/gcs_init_data.h — rebuild on
restart). Three backends here:

  * in-memory only (no persistence) — tests, ephemeral clusters;
  * file journal — append-only + startup compaction; survives a GCS
    process restart on the same disk (the default);
  * remote store — a socket client to an external `kv_server.py`
    process (Redis's role): survives loss of the head node's disk
    entirely. Writes stream through an ordered async queue (the
    reference's Redis writes are similarly async); reads are served
    from the in-memory tables, which a restart re-seeds from the
    remote snapshot before the GCS starts listening.
"""

from __future__ import annotations

import asyncio
import os
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import wire


class RemoteStoreClient:
    """Async client for the external KV store (kv_server.py).

    Writes are enqueued in order and drained by a writer task with
    retry — a transient store outage delays persistence but never
    blocks a GCS handler. The failure detector (GcsServer) decides when
    an outage is fatal; this client just keeps trying."""

    def __init__(self, address: str):
        from .rpc import RpcClient

        self.address = address
        self._client = RpcClient(address)
        self._queue: deque = deque()
        self._wake = asyncio.Event()
        self._writer_task: Optional[asyncio.Task] = None
        self._closed = False

    async def connect(self, timeout: float = 10.0) -> None:
        await self._client.connect(timeout=timeout)
        self._writer_task = asyncio.ensure_future(self._writer_loop())

    async def snapshot(self) -> List[Tuple[str, str, bytes]]:
        records = await self._client.call("store_snapshot", {}, timeout=60)
        return [(ns, key, val) for ns, key, val in records]

    def write(self, op: str, ns: str, key: str,
              val: Optional[bytes]) -> None:
        self._queue.append((op, ns, key, val))
        self._wake.set()

    async def ping(self, timeout: float = 2.0) -> bool:
        from .rpc import RpcClient

        # Dedicated throwaway probe connection per ping: the shared
        # client's reconnect lock is held for seconds at a time by the
        # durability writer's retries during a store outage, which would
        # stretch each probe far past its budget and stall the failure
        # detector's strike clock — the health probe must never share
        # fate with bulk writes. A fresh connect also recovers naturally
        # once the store comes back (no sticky closed=True transport).
        probe = RpcClient(self.address)
        try:
            async def _probe() -> bool:
                await probe.connect(timeout=timeout)
                return bool(await probe.call(
                    "store_ping", {}, timeout=timeout))

            return bool(await asyncio.wait_for(_probe(), timeout))
        except Exception:
            return False
        finally:
            try:
                await probe.close()
            except Exception:  # graftlint: ignore[swallow] — probe conn
                pass  # teardown; there is nothing to salvage

    async def flush(self, timeout: float = 10.0) -> None:
        """Wait until every enqueued write has been ACKED by the store
        (writes stay in the queue until their batch RPC succeeds, so
        queue-empty means durably delivered, not merely in flight).
        Raises TimeoutError when writes remain — a silent return would
        let close() discard the tail as if it were drained."""
        deadline = asyncio.get_event_loop().time() + timeout
        while self._queue and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.01)
        if self._queue:
            raise TimeoutError(
                f"{len(self._queue)} writes still un-ACKed by the "
                f"external store after {timeout}s")

    async def _writer_loop(self) -> None:
        import itertools

        # durability daemon: retrying the external store forever (0.5s
        # cadence, call_retrying already backs off per call) is the
        # point — dropping the queue on a persistent outage is the one
        # unacceptable outcome. close() bounds it via flush(timeout).
        while True:  # graftlint: ignore[rpc-timeout]
            if not self._queue:
                if self._closed:
                    return  # drained: safe to exit
                self._wake.clear()
                if self._closed:  # closed raced the clear
                    return
                await self._wake.wait()
                continue
            # peek a batch; it leaves the queue only on ACK, so a crash
            # or close() mid-RPC can never drop acknowledged-looking
            # writes (new appends only touch the right end — safe)
            batch = list(itertools.islice(self._queue, 512))
            try:
                await self._client.call_retrying(
                    "store_write_batch", {"ops": batch},
                    attempts=5, per_try_timeout=5.0)
            except Exception:
                await asyncio.sleep(0.5)
                continue
            for _ in range(len(batch)):
                self._queue.popleft()

    async def close(self) -> None:
        # drain BEFORE tearing down: dropping the tail of the write
        # stream at clean shutdown would hand a replacement head stale
        # tables — the exact failure this backend exists to prevent
        try:
            await self.flush(timeout=10.0)
        except TimeoutError as e:
            import sys

            print(f"[gcs] WARNING: external store close dropped writes "
                  f"({e}); a replacement head may see stale tables",
                  file=sys.stderr)
        self._closed = True
        self._wake.set()
        if self._writer_task is not None:
            self._writer_task.cancel()
        await self._client.close()


class Storage:
    """In-memory KV tables + optional persistence backend.

    `journal_path` — append-only local file, compacted at startup (every
    record rewritten at the current wire version: the journal migration
    path). `remote` — a RemoteStoreClient; callers must `await
    load_remote()` before serving (GcsServer.start does)."""

    def __init__(self, journal_path: Optional[str] = None,
                 remote: Optional[RemoteStoreClient] = None):
        self._kv: Dict[str, Dict[str, bytes]] = {}
        self._journal_path = journal_path
        self._journal = None
        self._remote = remote
        if remote is not None:
            # the external store is AUTHORITATIVE: replaying a stale
            # local journal under it would resurrect records another
            # head already deleted remotely (and re-compact them into
            # the journal). Remote mode therefore journals nothing
            # locally — exactly the reference's Redis mode.
            self._journal_path = None
        elif journal_path:
            self._replay(journal_path)
            self._compact(journal_path)
            self._journal = open(journal_path, "ab")

    @classmethod
    def open_readonly(cls, journal_path: str) -> "Storage":
        """Replay a journal into memory WITHOUT compacting it or opening
        an append handle — the postmortem reader's path: inspecting a
        dead (or still-running — another process may own the file)
        cluster's tables must never mutate them."""
        st = cls.__new__(cls)
        st._kv = {}
        st._journal_path = None
        st._journal = None
        st._remote = None
        st._replay(journal_path)
        return st

    # ---- local journal ----
    def _compact(self, path: str) -> None:
        tmp = path + ".compact"
        with open(tmp, "wb") as f:
            for ns, table in self._kv.items():
                for key, val in table.items():
                    body = wire.journal_encode("put", ns, key, val)
                    f.write(len(body).to_bytes(4, "little") + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _replay(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break
                length = int.from_bytes(header, "little")
                body = f.read(length)
                if len(body) < length:
                    break
                op, ns, key, val = wire.journal_decode(body)
                if op == "put":
                    self._kv.setdefault(ns, {})[key] = val
                elif op == "del":
                    self._kv.get(ns, {}).pop(key, None)

    def _log(self, op: str, ns: str, key: str, val: Optional[bytes]) -> None:
        if self._journal is not None:
            body = wire.journal_encode(op, ns, key, val)
            self._journal.write(len(body).to_bytes(4, "little") + body)
            self._journal.flush()
        if self._remote is not None:
            self._remote.write(op, ns, key, val)

    # ---- remote backend ----
    async def load_remote(self) -> None:
        for ns, key, val in await self._remote.snapshot():
            self._kv.setdefault(ns, {})[key] = val

    # ---- table interface ----
    def put(self, ns: str, key: str, val: bytes) -> None:
        self._kv.setdefault(ns, {})[key] = val
        self._log("put", ns, key, val)

    def get(self, ns: str, key: str) -> Optional[bytes]:
        return self._kv.get(ns, {}).get(key)

    def delete(self, ns: str, key: str) -> bool:
        existed = key in self._kv.get(ns, {})
        self._kv.get(ns, {}).pop(key, None)
        self._log("del", ns, key, None)
        return existed

    def keys(self, ns: str, prefix: str = "") -> List[str]:
        return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    def records(self):
        """Every (ns, key, value) — the snapshot interface kv_server
        serves and RemoteStoreClient.snapshot consumes."""
        for ns, table in self._kv.items():
            for key, val in table.items():
                yield ns, key, val

    def close(self):
        if self._journal is not None:
            self._journal.close()
