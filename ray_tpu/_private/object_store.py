"""Host object store: shared-memory segments + in-process memory store.

TPU-native analog of plasma (ref: src/ray/object_manager/plasma/store.h:55,
client.h:166 — dlmalloc over shm, unix-socket + mmap clients). Re-designed for
the TPU data path instead of translated:

 * one mmap'd file per object under /dev/shm (tmpfs) — creators write
   serialized bytes directly into the mapping, then seal via atomic rename, so
   cross-process visibility needs no fd-passing protocol (the reference's
   fling.cc) and readers map lazily;
 * sealed buffers are page-aligned and contiguous, so `jax.device_put` can DMA
   host->HBM without an intermediate copy (the Data->HBM fast path);
 * small objects bypass shm entirely and live in the owner's in-process memory
   store (ref: core_worker/store_provider/memory_store/), traveling inline on
   the RPC plane.

Eviction is LRU over sealed, unpinned objects (ref: plasma/eviction_policy.h).
"""

from __future__ import annotations

import asyncio
import mmap
import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import failpoints, locking
from .ids import ObjectID
from ..util.tracing import record_lane_event

_SHM_ROOT = "/dev/shm"

# process-wide spill/restore I/O counters (pure I/O time, excluding
# admission waits) — bench_envelope reads these for per-stage throughput
IO_STATS = {"spill_bytes": 0, "spill_s": 0.0,
            "restore_bytes": 0, "restore_s": 0.0}
_IO_STATS_LOCK = threading.Lock()


def _bump_io_stats(kind: str, nbytes: int, seconds: float) -> None:
    with _IO_STATS_LOCK:
        IO_STATS[kind + "_bytes"] += nbytes
        IO_STATS[kind + "_s"] += seconds


class ObjectStoreFullError(RuntimeError):
    pass


class InProgress:
    """Streaming-creation handle: the cut-through watermark.

    Registered per process while an object is being received (transfer
    plane) or restored (spill); ``watermark`` is the count of contiguous
    bytes already written at the front of ``buf``. Readers — the
    TransferServer relaying a broadcast, a peer's RPC chunk pull — wait
    for the watermark to pass their range and then serve straight from
    the unsealed mapping, so an interior broadcast-tree node forwards
    chunks as they arrive instead of store-and-forwarding the whole
    object (tree depth stops multiplying latency).

    Writers may advance from any thread (spill restore runs in I/O
    worker threads); waiters are asyncio futures woken through their own
    loop. ``finish(failed=True)`` (abort, reclaimed seal) wakes everyone
    so a dead upstream fails children fast instead of stranding them."""

    __slots__ = ("oid", "size", "buf", "watermark", "done", "failed",
                 "started_at", "last_progress_t", "_lock", "_waiters")

    def __init__(self, oid: ObjectID, size: int, buf: memoryview):
        self.oid = oid
        self.size = size
        self.buf = buf
        self.watermark = 0
        self.done = False
        self.failed = False
        # stall sentinel reads these: a pull whose watermark stopped
        # moving shows up as (now - last_progress_t) in stalled_pulls()
        self.started_at = time.time()
        self.last_progress_t = self.started_at
        self._lock = locking.make_lock("InProgress._lock")
        self._waiters: List[tuple] = []

    def advance(self, watermark: int) -> None:
        with self._lock:
            if self.done or watermark <= self.watermark:
                return
            self.watermark = watermark
            self.last_progress_t = time.time()
            ready = [w for w in self._waiters if w[0] <= watermark]
            self._waiters = [w for w in self._waiters if w[0] > watermark]
        for _, loop, fut in ready:
            self._wake(loop, fut)

    def finish(self, failed: bool) -> None:
        with self._lock:
            if self.done:
                return
            self.done = True
            self.failed = failed
            if not failed:
                self.watermark = self.size
            ready, self._waiters = self._waiters, []
        for _, loop, fut in ready:
            self._wake(loop, fut)

    @staticmethod
    def _wake(loop, fut) -> None:
        def _set():
            if not fut.done():
                fut.set_result(None)
        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # waiter's loop already closed

    async def wait_for(self, threshold: int, timeout: float) -> bool:
        """True once watermark >= threshold (seal counts); False when the
        creation failed or the watermark stalls past `timeout`."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if self.watermark >= threshold:
                    return True
                if self.done:
                    return False
                loop = asyncio.get_event_loop()
                fut = loop.create_future()
                entry = (threshold, loop, fut)
                self._waiters.append(entry)
            try:
                await asyncio.wait_for(
                    fut, max(0.0, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                with self._lock:
                    try:
                        self._waiters.remove(entry)
                    except ValueError:
                        pass
                return self.watermark >= threshold


class _RestoreGate:
    """Bytes-in-flight admission for spill restores: the thread-side
    sibling of PullManager.acquire_bytes (same semantics — the sole
    in-flight restore always admits so one over-budget object can't
    wedge; otherwise wait for releases), sharing the same configured
    budget (``object_transfer_max_inflight_bytes``) so concurrent
    restores can't blow the store past what pulls may."""

    def __init__(self, budget: int):
        self.budget = budget
        self._inflight = 0
        self._count = 0
        self._cond = locking.make_condition("_RestoreGate._cond")

    def acquire(self, nbytes: int) -> None:
        with self._cond:
            while self._count and self._inflight + nbytes > self.budget:
                self._cond.wait(timeout=1.0)
            self._inflight += nbytes
            self._count += 1

    def release(self, nbytes: int) -> None:
        with self._cond:
            self._inflight -= nbytes
            self._count -= 1
            self._cond.notify_all()


_restore_gate: Optional[_RestoreGate] = None
_spill_pool: Optional[ThreadPoolExecutor] = None
_pool_lock = threading.Lock()


def _get_restore_gate() -> _RestoreGate:
    global _restore_gate
    if _restore_gate is None:
        from .config import global_config
        with _pool_lock:
            if _restore_gate is None:
                _restore_gate = _RestoreGate(
                    global_config().object_transfer_max_inflight_bytes)
    return _restore_gate


def _get_spill_pool() -> ThreadPoolExecutor:
    global _spill_pool
    if _spill_pool is None:
        from .config import global_config
        with _pool_lock:
            if _spill_pool is None:
                _spill_pool = ThreadPoolExecutor(
                    max_workers=max(
                        1, global_config().object_spill_io_workers),
                    thread_name_prefix="rtpu-spill-io")
    return _spill_pool


def _parallel_io(size: int, chunk: int, run_chunk, on_frontier=None) -> None:
    """Fan `size` bytes of positional I/O over the spill pool in `chunk`
    pieces. `run_chunk(offset, end)` moves one piece (any worker thread);
    `on_frontier(nbytes)` fires as the CONTIGUOUS completed prefix grows
    (the restore watermark). Worker exceptions propagate to the caller."""
    n_chunks = max(1, (size + chunk - 1) // chunk)
    from .config import global_config
    workers = max(1, min(global_config().object_spill_io_workers, n_chunks))
    if workers == 1 or n_chunks == 1:
        off = 0
        while off < size:
            end = min(off + chunk, size)
            run_chunk(off, end)
            off = end
            if on_frontier is not None:
                on_frontier(off)
        return
    lock = threading.Lock()
    state = {"next": 0, "frontier": 0}
    done = bytearray(n_chunks)

    def work():
        while True:
            with lock:
                i = state["next"]
                if i >= n_chunks:
                    return
                state["next"] = i + 1
            off = i * chunk
            run_chunk(off, min(off + chunk, size))
            with lock:
                done[i] = 1
                f = state["frontier"]
                while f < n_chunks and done[f]:
                    f += 1
                state["frontier"] = f
                frontier_bytes = size if f >= n_chunks else f * chunk
            if on_frontier is not None:
                on_frontier(frontier_bytes)

    pool = _get_spill_pool()
    futs = [pool.submit(work) for _ in range(workers)]
    for fut in futs:
        fut.result()


@dataclass
class _Entry:
    path: str
    size: int
    mm: Optional[mmap.mmap] = None
    pin_count: int = 0
    sealed: bool = True
    last_access: float = field(default_factory=time.monotonic)
    # per-process unsealed staging file (pid-suffixed: two processes
    # re-creating the same object must not write the same tmp file)
    tmp_path: str = ""
    # whether THIS handle reserved the index entry (abort must not
    # release someone else's live reservation)
    owns_reservation: bool = True


class SharedObjectStore:
    """Per-node shared-memory object store. Any process on the node may
    instantiate this with the same session name; the filesystem is the shared
    metadata substrate, the node manager is the authority on existence."""

    def __init__(self, session_name: str, capacity_bytes: int, create_dir: bool = True):
        # session_name may be a relative namespace (placed under /dev/shm) or
        # an absolute store directory (worker processes inherit their node's)
        self.dir = session_name if session_name.startswith("/") \
            else os.path.join(_SHM_ROOT, session_name)
        self.capacity = capacity_bytes
        if create_dir:
            os.makedirs(self.dir, exist_ok=True)
        # Spill-on-pressure (ref: raylet/local_object_manager.h:45,
        # _private/external_storage.py): sealed LRU victims move to a
        # disk directory instead of dying; restore is lazy on access.
        # Shared per store dir so every process on the node can restore.
        # NOTE: the default lives under TEMP_ROOT (/tmp) — on distros
        # that mount /tmp as tmpfs that is still RAM; deployments there
        # must point RAY_TPU_OBJECT_SPILLING_DIR at a real disk (the
        # reference has the same contract via its spilling config).
        from .config import TEMP_ROOT, global_config as _gc

        cfg = _gc()
        if cfg.object_spilling_enabled:
            self.spill_dir = cfg.object_spilling_dir or os.path.join(
                TEMP_ROOT, "spill", os.path.basename(self.dir.rstrip("/")))
            os.makedirs(self.spill_dir, exist_ok=True)
        else:
            self.spill_dir = None
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()
        self._lock = locking.make_lock("SharedObjectStore._lock")
        self._used = 0
        # control-plane pin counts THIS process issued (memory
        # attribution needs them readable; _entries only tracks pins of
        # objects this process has mapped, and the native index records
        # pins but does not expose per-object counts)
        self._pins: Dict[ObjectID, int] = {}
        # streaming creations (cut-through watermark), per process
        self._inprogress: Dict[ObjectID, InProgress] = {}
        # per-oid single-flight gate for spill restores (threads get()ing
        # the same spilled object wait for the winner's seal)
        self._restoring: Dict[ObjectID, threading.Event] = {}
        # fallback-path eviction staging (flushed outside self._lock)
        self._pending_spill_flush: list = []
        # Native index (C++ shared table, ray_tpu/_native): makes seal
        # state, capacity accounting, pins and LRU order node-global
        # facts across every process sharing this dir. Falls back to
        # pure-Python per-process accounting ONLY when the native lib is
        # unavailable — a failure to open an index that should exist is
        # loud, because mixed native/fallback handles on one dir would
        # fight over eviction authority.
        from .._native import NativeIndex, native_unavailable_reason

        if native_unavailable_reason() is None:
            self._idx = NativeIndex(os.path.join(self.dir, "index.bin"),
                                    capacity_bytes, data_dir=self.dir)
            if self.spill_dir:
                self._idx.set_spill_dir(self.spill_dir)
        else:
            self._idx = None

    # ---- paths ----
    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self.dir, oid.hex())

    # ---- write path ----
    def _reserve_native(self, oid: ObjectID, size: int) -> bool:
        """Node-global reservation through the C++ index. Victims' data
        files were already unlinked by the index UNDER ITS MUTEX (no
        race with a concurrent re-create's seal); here we only drop this
        process's stale mappings. Returns False when the object already
        exists in the index (a re-create: another process reserved or
        sealed it) — the caller still writes its own staging file and
        seal() renames it into place atomically, but this handle does
        NOT own the reservation."""
        rc, victims = self._idx.reserve(oid.binary(), size)
        if rc == -2:
            return False
        if rc != 0:
            raise ObjectStoreFullError(
                f"object store over capacity: need {size}, used "
                f"{self._idx.used()}, capacity {self._idx.capacity()} "
                f"(rc={rc})")
        for vid in victims:
            voi = ObjectID(vid)
            with self._lock:
                entry = self._entries.pop(voi, None)
                if entry is not None and entry.mm is not None:
                    try:
                        entry.mm.close()
                    except BufferError:
                        pass
            # the index staged spilled victims as <hex>.spilling (same
            # fs, under its mutex); the cross-fs copy to the spill dir
            # happens HERE, outside any lock
            self._flush_staged_spill(voi)
        return True

    def _flush_staged_spill(self, oid: ObjectID) -> None:
        if not self.spill_dir:
            return
        staged = os.path.join(self.dir, oid.hex() + ".spilling")
        if not os.path.exists(staged):
            return
        # after the staged-exists check so inert flushes stay free; a
        # raise propagates through _reserve_native to the putting caller
        failpoints.fire("spill.write")
        dest = os.path.join(self.spill_dir, oid.hex())
        try:
            try:
                size = os.path.getsize(staged)
            except OSError:
                size = 0
            wall0 = time.time()
            # same filesystem: O(1), nothing to parallelize
            os.rename(staged, dest)
            record_lane_event("spill", f"spill {oid.hex()[:12]}",
                              wall0, time.time(), bytes=size)
            return
        except FileNotFoundError:
            return
        except OSError:
            pass  # EXDEV — tmpfs store dir vs on-disk spill dir
        try:
            wall0 = time.time()
            t0 = time.monotonic()
            size = self._parallel_copy_file(staged, dest)
            _bump_io_stats("spill", size, time.monotonic() - t0)
            record_lane_event("spill", f"spill {oid.hex()[:12]}",
                              wall0, time.time(), bytes=size)
            os.unlink(staged)
        except (FileNotFoundError, OSError):
            try:
                os.unlink(dest + ".part")
            except OSError:
                pass

    def _parallel_copy_file(self, src: str, dest: str) -> int:
        """Cross-fs spill write: chunked multi-worker sendfile (pread/
        pwrite fallback) into dest+'.part', renamed into place only when
        complete — a crashed evictor must not leave a short file that
        looks like a finished spill. Returns bytes copied."""
        from .config import global_config

        chunk = max(64 << 10, global_config().object_spill_io_chunk_bytes)
        sfd = os.open(src, os.O_RDONLY)
        try:
            size = os.fstat(sfd).st_size
            part = dest + ".part"
            out0 = os.open(part, os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
                           0o600)
            try:
                if size:
                    os.ftruncate(out0, size)

                def copy_range(off, end):
                    # per-worker out fd: sendfile writes at the fd's own
                    # offset, shared fds would race on it
                    ofd = os.open(part, os.O_WRONLY)
                    try:
                        os.lseek(ofd, off, os.SEEK_SET)
                        pos = off
                        while pos < end:
                            try:
                                n = os.sendfile(ofd, sfd, pos, end - pos)
                            except OSError:
                                scratch = bytearray(
                                    min(chunk, end - pos))
                                n = os.preadv(sfd, [scratch], pos)
                                if n:
                                    os.pwrite(ofd, scratch[:n], pos)
                                    os.lseek(ofd, pos + n, os.SEEK_SET)
                            if n == 0:
                                raise OSError("spill source truncated")
                            pos += n
                    finally:
                        os.close(ofd)

                _parallel_io(size, chunk, copy_range)
            finally:
                os.close(out0)
            os.rename(part, dest)
            return size
        finally:
            os.close(sfd)

    def _flush_pending_spills(self) -> None:
        """Fallback-path staging flush, outside self._lock."""
        while True:
            with self._lock:
                if not self._pending_spill_flush:
                    return
                oid = self._pending_spill_flush.pop()
            self._flush_staged_spill(oid)

    def create(self, oid: ObjectID, size: int) -> memoryview:
        """Allocate an unsealed buffer; returns a writable view. Caller must
        seal() (or abort()) exactly once."""
        owns = True
        if self._idx is not None:
            owns = self._reserve_native(oid, size)
        else:
            with self._lock:
                self._maybe_evict(size)
                # Reserve capacity before dropping the lock so concurrent
                # creates can't collectively overshoot it.
                self._used += size
            self._flush_pending_spills()
        tmp = f"{self._path(oid)}.tmp.{os.getpid()}"
        try:
            fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o600)
            try:
                os.ftruncate(fd, max(size, 1))
                mm = mmap.mmap(fd, max(size, 1))
            finally:
                os.close(fd)
        except BaseException:
            if self._idx is not None:
                if owns:
                    self._idx.abort(oid.binary())
            else:
                with self._lock:
                    self._used -= size
            raise
        with self._lock:
            self._entries[oid] = _Entry(
                path=self._path(oid), size=size, mm=mm, sealed=False,
                tmp_path=tmp, owns_reservation=owns)
        return memoryview(mm)[:size]

    def put(self, oid: ObjectID, data: bytes) -> None:
        buf = self.create(oid, len(data))
        buf[:] = data
        self.seal(oid)

    def create_streaming(self, oid: ObjectID,
                         size: int) -> Tuple[memoryview, InProgress]:
        """create() plus a registered InProgress watermark handle, so
        readers in this process (TransferServer relay, RPC chunk serving)
        can stream already-received contiguous bytes before seal. seal()
        finishes the handle ok; abort() (or a reclaimed seal) fails it,
        waking blocked range readers with failure."""
        buf = self.create(oid, size)
        with self._lock:
            e = self._entries.get(oid)
            view = memoryview(e.mm)[:size] if (e is not None
                                               and e.mm is not None) else buf
        entry = InProgress(oid, size, view)
        with self._lock:
            # a concurrent streaming creation of the same oid keeps the
            # first registration (both write identical content; the
            # first seal/abort for the oid finishes it)
            self._inprogress.setdefault(oid, entry)
        return buf, entry

    def inprogress(self, oid: ObjectID) -> Optional[InProgress]:
        with self._lock:
            return self._inprogress.get(oid)

    def stalled_pulls(self, stall_after_s: float) -> List[dict]:
        """In-progress creations whose contiguous watermark has not
        advanced for `stall_after_s` seconds — the transfer stall
        detector's input (watermark registry doubles as progress meter)."""
        now = time.time()
        with self._lock:
            entries = list(self._inprogress.values())
        out = []
        for e in entries:
            if e.done:
                continue
            idle = now - e.last_progress_t
            if idle >= stall_after_s:
                out.append({
                    "object_id": e.oid.hex(),
                    "size": e.size,
                    "watermark": e.watermark,
                    "stalled_for_s": idle,
                    "age_s": now - e.started_at,
                })
        return out

    def _finish_inprogress(self, oid: ObjectID, failed: bool) -> None:
        with self._lock:
            entry = self._inprogress.pop(oid, None)
        if entry is not None:
            entry.finish(failed)

    def seal(self, oid: ObjectID) -> None:
        # before any state change: an injected seal fault must leave the
        # unsealed entry intact so abort/cleanup paths still work
        failpoints.fire("object.seal")
        with self._lock:
            entry = self._entries[oid]
            entry.mm.flush()
            os.rename(entry.tmp_path or entry.path + ".tmp", entry.path)
            entry.sealed = True
        if self._idx is not None:
            rc = self._idx.seal(oid.binary())
            if rc != 0:
                # The index reclaimed our reservation (stale-creation
                # sweep or a racing delete) — the renamed data file has
                # no index entry, so it would consume tmpfs capacity
                # that used() never accounts and could never be
                # evicted. Unlink it and surface the object as lost.
                with self._lock:
                    e = self._entries.pop(oid, None)
                    if e is not None and e.mm is not None:
                        try:
                            e.mm.close()
                        except BufferError:
                            pass
                try:
                    os.unlink(entry.path)
                except FileNotFoundError:
                    pass
                self._finish_inprogress(oid, failed=True)
                raise ObjectStoreFullError(
                    f"object {oid.hex()} lost at seal: index reservation "
                    f"was reclaimed (rc={rc}); re-put the object")
        self._finish_inprogress(oid, failed=False)

    def abort(self, oid: ObjectID) -> None:
        self._finish_inprogress(oid, failed=True)
        with self._lock:
            entry = self._entries.pop(oid, None)
            if entry is None:
                return
            if self._idx is not None:
                if entry.owns_reservation:
                    self._idx.abort(oid.binary())
            else:
                self._used -= entry.size
            if entry.mm is not None:
                try:
                    entry.mm.close()
                except BufferError:
                    pass  # relay readers hold views; unlink still reclaims
            paths = [entry.tmp_path] if entry.tmp_path else []
            # only the reservation owner may take down the sealed file
            if entry.owns_reservation:
                paths.append(entry.path)
            for p in paths:
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass

    # ---- read path ----
    def get(self, oid: ObjectID) -> Optional[memoryview]:
        """Map a sealed object; zero-copy view. None if absent/unsealed.
        Objects spilled to disk are transparently restored first."""
        view = self._get_once(oid)
        if view is not None:
            return view
        # under capacity thrash a concurrent restore's eviction pressure
        # can re-spill the object between our lookup and mapping (or the
        # index still shows another thread's not-yet-sealed restore);
        # while any evidence of the object survives, retry — None must
        # mean ABSENT, not "lost a race"
        for attempt in range(64):
            if not self.contains(oid):
                return None
            time.sleep(min(0.05, 0.001 * (attempt + 1)))
            view = self._get_once(oid)
            if view is not None:
                return view
        return None

    def _get_once(self, oid: ObjectID) -> Optional[memoryview]:
        if self._idx is not None:
            # index is the authority (and the lookup is the LRU touch):
            # a locally-cached mmap whose entry another process evicted
            # must not serve stale data
            state, _ = self._idx.lookup(oid.binary())
            if state != 0:
                with self._lock:
                    entry = self._entries.get(oid)
                    # keep our own not-yet-sealed create mapping; drop
                    # anything else the index no longer knows
                    if entry is not None and entry.sealed:
                        self._entries.pop(oid, None)
                        if entry.mm is not None:
                            try:
                                entry.mm.close()
                            except BufferError:
                                pass
                if state == 1 and self._restore_from_spill(oid):
                    pass  # restored: fall through and serve it
                else:
                    return None
        with self._lock:
            entry = self._entries.get(oid)
            if entry is not None and entry.sealed and entry.mm is not None:
                entry.last_access = time.monotonic()
                self._entries.move_to_end(oid)
                return memoryview(entry.mm)[: entry.size]
        # Not mapped locally — another process may have sealed it.
        path = self._path(oid)
        try:
            fd = os.open(path, os.O_RDWR)
        except FileNotFoundError:
            if self._idx is None and self._restore_from_spill(oid):
                try:
                    fd = os.open(path, os.O_RDWR)
                except FileNotFoundError:
                    return None
            else:
                return None
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        with self._lock:
            # A concurrent get() may have mapped it while we were outside
            # the lock; keep the winner, drop our duplicate mapping.
            entry = self._entries.get(oid)
            if entry is not None and entry.mm is not None:
                mm.close()
            else:
                if self._idx is None:
                    # Mapping a foreign-sealed object grows the store
                    # too: evict LRU victims (or raise) first. (With the
                    # native index the object was accounted node-globally
                    # at creation — mapping it adds nothing.)
                    try:
                        self._maybe_evict(size)
                    except ObjectStoreFullError:
                        mm.close()
                        raise
                    self._used += size
                entry = _Entry(path=path, size=size, mm=mm)
                self._entries[oid] = entry
            entry.last_access = time.monotonic()
            self._entries.move_to_end(oid)
            return memoryview(entry.mm)[: entry.size]

    def _spill_path(self, oid: ObjectID) -> Optional[str]:
        if not self.spill_dir:
            return None
        return os.path.join(self.spill_dir, oid.hex())

    def _restore_from_spill(self, oid: ObjectID) -> bool:
        """Restore a spilled object: chunked multi-worker preadv straight
        from the spill file into the unsealed shm mapping (no
        intermediate bytes — the old whole-file read paid a full extra
        copy and ran on one thread), then drop the disk copy. Admission
        rides the restore byte gate (PullManager-budget sibling) so
        concurrent restores can't blow the store. The contiguous-read
        frontier advances the InProgress watermark, so transfer-plane
        pullers of a RESTORING object stream behind the restore instead
        of waiting for its seal. Restores are single-flight per object
        per process (threads racing get() wait for the winner's seal).
        Also serves objects still sitting in the
        same-fs ".spilling" staging name (the evictor flushes those to
        the spill dir outside the index lock — a reader can land in that
        window, or after an evictor crash)."""
        path = self._spill_path(oid)
        if path is None:
            return False
        # one restore per object per process: two threads get()ing the
        # same spilled object would both create() the same tmp path
        # (same pid -> same name) and O_TRUNC it under the other's live
        # mapping; losers wait for the winner and re-serve its result
        with self._lock:
            ev = self._restoring.get(oid)
            waiter = ev is not None
            if not waiter:
                ev = threading.Event()
                self._restoring[oid] = ev
        if waiter:
            ev.wait(timeout=600.0)
            return True  # winner sealed it (or get() finds it absent)
        try:
            return self._do_restore_from_spill(oid, path)
        finally:
            with self._lock:
                self._restoring.pop(oid, None)
            ev.set()

    def _do_restore_from_spill(self, oid: ObjectID, path: str) -> bool:
        sfd = -1
        for candidate in (path, os.path.join(self.dir,
                                             oid.hex() + ".spilling")):
            try:
                sfd = os.open(candidate, os.O_RDONLY)
                path = candidate
                break
            except OSError:
                continue
        if sfd < 0:
            return False
        gate = _get_restore_gate()
        acquired = 0
        try:
            size = os.fstat(sfd).st_size
            gate.acquire(size)
            acquired = size
            try:
                buf, entry = self.create_streaming(oid, size)
            except (ObjectStoreFullError, OSError):
                return False
            from .config import global_config

            chunk = max(64 << 10,
                        global_config().object_spill_io_chunk_bytes)

            def read_range(off, end):
                pos = off
                while pos < end:
                    n = os.preadv(sfd, [buf[pos:end]], pos)
                    if n == 0:
                        raise OSError("spill file truncated mid-restore")
                    pos += n

            wall0 = time.time()
            t0 = time.monotonic()
            try:
                _parallel_io(size, chunk, read_range,
                             on_frontier=entry.advance)
            except BaseException:
                buf.release()
                self.abort(oid)
                raise
            _bump_io_stats("restore", size, time.monotonic() - t0)
            record_lane_event("restore", f"restore {oid.hex()[:12]}",
                              wall0, time.time(), bytes=size)
            buf.release()
            # pin across seal -> spill-copy unlink: the instant seal()
            # lands, capacity pressure may evict this object again and
            # re-stage it into the spill dir — unlinking then would
            # delete the only surviving copy (observed as get() -> None
            # under restore thrash)
            self.pin(oid)
            try:
                try:
                    self.seal(oid)
                except (ObjectStoreFullError, OSError):
                    return False
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
            finally:
                self.unpin(oid)
        except OSError:
            return False
        finally:
            if acquired:
                gate.release(acquired)
            os.close(sfd)
        return True

    def contains(self, oid: ObjectID) -> bool:
        if self._idx is not None:
            # existence probe: no LRU touch (polling must not distort
            # node-global eviction order)
            if self._idx.lookup(oid.binary(), touch=False)[0] == 0:
                return True
        else:
            with self._lock:
                entry = self._entries.get(oid)
                if entry is not None and entry.sealed:
                    return True
            if os.path.exists(self._path(oid)):
                return True
        path = self._spill_path(oid)
        if path is None:
            return False
        return (os.path.exists(path)
                or os.path.exists(os.path.join(self.dir,
                                               oid.hex() + ".spilling")))

    def size(self, oid: ObjectID) -> int:
        """Sealed size WITHOUT mapping the object, touching LRU order,
        or restoring a spilled copy (admission/budget checks must not
        re-inflate the memory they exist to bound). 0 = unknown."""
        if self._idx is not None:
            state, size = self._idx.lookup(oid.binary(), touch=False)
            if state == 0:
                return size
        else:
            with self._lock:
                entry = self._entries.get(oid)
                if entry is not None and entry.sealed:
                    return entry.size
            try:
                return os.path.getsize(self._path(oid))
            except OSError:
                pass
        path = self._spill_path(oid)
        if path is not None:
            try:
                return os.path.getsize(path)
            except OSError:
                pass
        return 0

    def pin(self, oid: ObjectID) -> None:
        if self._idx is not None:
            self._idx.pin(oid.binary())  # node-global: protects from
            # evictions by ANY process sharing the store
        with self._lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1
            entry = self._entries.get(oid)
            if entry is not None:
                entry.pin_count += 1

    def unpin(self, oid: ObjectID) -> None:
        if self._idx is not None:
            self._idx.unpin(oid.binary())
        with self._lock:
            count = self._pins.get(oid, 0) - 1
            if count <= 0:
                self._pins.pop(oid, None)
            else:
                self._pins[oid] = count
            entry = self._entries.get(oid)
            if entry is not None and entry.pin_count > 0:
                entry.pin_count -= 1

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._pins.pop(oid, None)
            entry = self._entries.pop(oid, None)
            if entry is not None:
                if self._idx is None:
                    self._used -= entry.size
                if entry.mm is not None:
                    try:
                        entry.mm.close()
                    except BufferError:
                        pass  # live memoryviews; file unlink still reclaims on close
        if self._idx is not None:
            self._idx.delete(oid.binary())
        try:
            os.unlink(self._path(oid))
        except FileNotFoundError:
            pass
        spath = self._spill_path(oid)
        if spath is not None:
            for p in (spath, os.path.join(self.dir,
                                          oid.hex() + ".spilling")):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass

    # ---- accounting / eviction ----
    def used_bytes(self) -> int:
        if self._idx is not None:
            return self._idx.used()
        return self._used

    def usage_report(self) -> dict:
        """Node-global object inventory for memory attribution
        (state.memory_report): scans the shared store directory — the
        substrate every process on the node writes — rather than this
        process's ``_entries``, so objects created by sibling processes
        count too (both native-index and fallback modes). Pin counts
        merge this process's control-plane pins (the raylet, which
        serves this per node, is the process that executes owner
        pin/unpin RPCs) with mapped-entry pins."""
        now = time.time()
        hex_len = ObjectID.SIZE * 2
        objects: Dict[str, dict] = {}

        def _scan(directory: str, spilled: bool) -> None:
            try:
                with os.scandir(directory) as it:
                    for de in it:
                        name = de.name
                        if len(name) != hex_len:
                            continue
                        try:
                            bytes.fromhex(name)
                            st = de.stat()
                        except (ValueError, OSError):
                            continue
                        objects[name] = {
                            "size": st.st_size,
                            "age_s": max(0.0, now - st.st_mtime),
                            "pinned": 0,
                            "sealed": True,
                            "spilled": spilled,
                        }
            except OSError:
                pass

        _scan(self.dir, spilled=False)
        if self.spill_dir:
            _scan(self.spill_dir, spilled=True)
        with self._lock:
            for oid, entry in self._entries.items():
                rec = objects.get(oid.hex())
                if rec is not None:
                    rec["pinned"] = max(rec["pinned"], entry.pin_count)
                    rec["sealed"] = entry.sealed
            for oid, count in self._pins.items():
                rec = objects.get(oid.hex())
                if rec is not None and count > 0:
                    rec["pinned"] = max(rec["pinned"], count)
        return {
            "used_bytes": self.used_bytes(),
            "capacity_bytes": self.capacity,
            "spill_bytes": sum(r["size"] for r in objects.values()
                               if r["spilled"]),
            "num_objects": len(objects),
            "objects": objects,
        }

    def _maybe_evict(self, incoming: int) -> None:
        # caller holds self._lock
        if self._used + incoming <= self.capacity:
            return
        # Hopeless requests must not destroy the cache: check that evicting
        # every unpinned sealed entry would actually make room first.
        evictable = sum(e.size for e in self._entries.values()
                        if e.sealed and e.pin_count == 0)
        if self._used - evictable + incoming > self.capacity:
            raise ObjectStoreFullError(
                f"object store over capacity: need {incoming}, used "
                f"{self._used} ({evictable} evictable), capacity "
                f"{self.capacity}")
        target = self.capacity - incoming
        victims = []
        for oid, entry in self._entries.items():  # OrderedDict == LRU order
            if self._used - sum(v[1].size for v in victims) <= target:
                break
            if entry.sealed and entry.pin_count == 0:
                victims.append((oid, entry))
        for oid, entry in victims:
            self._entries.pop(oid, None)
            self._used -= entry.size
            if entry.mm is not None:
                try:
                    entry.mm.close()
                except BufferError:
                    pass
            try:
                if self.spill_dir:
                    # stage under the lock (same-fs rename, O(1)); the
                    # caller flushes to the spill dir after releasing it
                    os.rename(entry.path, entry.path + ".spilling")
                    self._pending_spill_flush.append(oid)
                else:
                    os.unlink(entry.path)
            except (FileNotFoundError, OSError):
                pass
        if self._used + incoming > self.capacity:
            raise ObjectStoreFullError(
                f"object store over capacity: need {incoming}, used {self._used}, "
                f"capacity {self.capacity} (all remaining objects pinned/unsealed)"
            )

    def destroy(self) -> None:
        with self._lock:
            for entry in self._entries.values():
                if entry.mm is not None:
                    try:
                        entry.mm.close()
                    except BufferError:
                        pass
            self._entries.clear()
            self._used = 0
        if self._idx is not None:
            self._idx.close()
            self._idx = None
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)
        if self.spill_dir:
            shutil.rmtree(self.spill_dir, ignore_errors=True)


class MemoryStore:
    """In-process store for small/inlined objects and errors
    (ref: core_worker/store_provider/memory_store/)."""

    def __init__(self):
        self._objects: Dict[ObjectID, bytes] = {}
        self._lock = locking.make_lock("MemoryStore._lock")
        self._waiters: Dict[ObjectID, list] = {}

    def put(self, oid: ObjectID, data: bytes) -> None:
        with self._lock:
            self._objects[oid] = data
            waiters = self._waiters.pop(oid, [])
        for ev in waiters:
            ev.set()

    def get(self, oid: ObjectID) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(oid)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._objects

    def usage_report(self) -> dict:
        """In-process (inlined small objects) usage for memory_report."""
        with self._lock:
            return {"num_objects": len(self._objects),
                    "used_bytes": sum(len(v) for v
                                      in self._objects.values())}

    def wait_handle(self, oid: ObjectID) -> threading.Event:
        ev = threading.Event()
        with self._lock:
            if oid in self._objects:
                ev.set()
            else:
                self._waiters.setdefault(oid, []).append(ev)
        return ev

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._objects.pop(oid, None)
